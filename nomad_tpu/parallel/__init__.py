"""Device-mesh parallelism for the placement engine."""
from .sharding import (  # noqa: F401
    batched_place_scan,
    batched_scan_shardings,
    make_mesh,
)
