"""Device-mesh parallelism for the placement engine."""
from .sharding import (  # noqa: F401
    batched_place_scan,
    make_mesh,
    scan_input_shardings,
)
