"""Mesh construction and sharding specs for the placement scan.

The scale axes of this domain map onto a 2-D ``jax.sharding.Mesh``:

  "evals" — data parallelism over independent evaluations (each eval's scan
            is independent; the broker dequeues many at once). The analog of
            DP in an ML workload.
  "nodes" — model/sequence parallelism over the cluster's node axis: every
            [N]-shaped array (capacity, masks, scores) is sharded across
            chips, and XLA inserts the all-gather/all-reduce/argmax
            collectives the ring-ordered selection needs. The analog of
            TP/SP: the "long context" here is the 5K-node (and beyond)
            cluster state.

We use GSPMD via jit + NamedSharding rather than hand-written shard_map:
the scan body is dominated by elementwise ops, cumsums and reductions over
the node axis, all of which XLA partitions well.
"""
from __future__ import annotations

from typing import Optional, Tuple


def make_mesh(n_devices: Optional[int] = None, eval_parallel: int = 1):
    """Build a ("evals", "nodes") mesh over the available devices."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    ep = max(1, min(eval_parallel, n))
    while n % ep != 0:
        ep -= 1
    grid = np.asarray(devices).reshape(ep, n // ep)
    return Mesh(grid, ("evals", "nodes"))


def batched_scan_shardings(mesh):
    """(static, carry, xs) NamedShardings for the FULLY-batched scan
    (engine._build_batched_scan): every array carries a leading eval axis
    (concurrent evals see different snapshots/node sets/jobs, so node
    tables batch too). Eval axis shards over "evals"; node dims over
    "nodes"; small per-TG/spread tables replicate within an eval shard.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    e = "evals"
    static = (
        ns(e, "nodes", None),        # totals [B, N, D]
        ns(e, "nodes", None),        # reserved [B, N, D]
        ns(e, None, None),           # asks [B, G, D]
        ns(e, None, "nodes"),        # feat_packed [B, G, N] (uint8 lanes)
        ns(e, None, "nodes"),        # aff_score [B, G, N]
        ns(e, None),                 # desired_counts [B, G]
        ns(e, None),                 # dh_job [B, G]
        ns(e, None),                 # dh_tg [B, G]
        ns(e, None),                 # limits [B, G]
        ns(e, None, None, "nodes"),  # spread_vids [B, G, S, N]
        ns(e, None, None, None),     # spread_desired [B, G, S, V]
        ns(e, None, None),           # spread_weights [B, G, S]
        ns(e, None, None),           # spread_has_targets [B, G, S]
        ns(e, None, None),           # spread_active [B, G, S]
        ns(e, None),                 # sum_spread_weights [B, G]
        ns(e),                       # n_real [B]
        ns(e, None, "nodes", None),  # e_ask [B, G, N, 2]
        ns(e, None, "nodes"),        # dp_vids [B, D, N]
        ns(e, None),                 # dp_limit [B, D]
        ns(e, None, None),           # dp_applies [B, G, D]
        ns(e, "nodes", None, None),  # pre_res [B, N, C, 4]
        ns(e, "nodes", None),        # pre_prio [B, N, C]
        ns(e, "nodes", None),        # pre_elig [B, N, C]
        ns(e, "nodes", None),        # pre_mp [B, N, C]
        ns(e, "nodes", None),        # pre_gid [B, N, C]
        ns(e, "nodes", None, None),  # pre_evf [B, N, C, 2]
    )
    carry = (
        ns(e, "nodes", None),        # used [B, N, D]
        ns(e, None, "nodes"),        # tg_counts [B, G, N]
        ns(e, "nodes"),              # job_counts [B, N]
        ns(e, None, None, None),     # spread_counts [B, G, S, V]
        ns(e, None, None, None),     # spread_entry [B, G, S, V]
        ns(e),                       # offset [B]
        ns(e, None),                 # failed [B, G]
        ns(e, "nodes", None),        # e_base [B, N, 2]
        ns(e, None, None),           # dp_counts [B, D, V]
        ns(e, "nodes", None),        # pre_alive [B, N, C]
        ns(e, "nodes", None),        # pre_remaining [B, N, 3]
        ns(e, None),                 # pre_counts [B, GP]
    )
    xs = (
        ns(e, None),                 # tg_idx [B, P]
        ns(e, None, None),           # penalty_idx [B, P, K]
        ns(e, None),                 # evict_node [B, P]
        ns(e, None, None),           # evict_res [B, P, D]
        ns(e, None),                 # evict_tg [B, P]
        ns(e, None),                 # limit_p [B, P]
        ns(e, None),                 # sum_sw_p [B, P]
        ns(e, None, None),           # ev_factor [B, P, 2]
        ns(e, None, None),           # rev_factor [B, P, 2]
        ns(e, None, None),           # forced_node [B, P, W]
    )
    return static, carry, xs


def batched_place_scan(mesh):
    """The mesh-sharded, eval-batched placement scan over FULLY batched
    inputs (node tables included — see batched_scan_shardings). Thin
    wrapper over the ONE builder (engine._build_batched_scan); the
    production path is tpu.batcher.DeviceBatcher, which pads/stacks real
    EncodedEvals and uses these same shardings.
    """
    from ..tpu.engine import _build_batched_scan

    return _build_batched_scan(in_shardings=batched_scan_shardings(mesh))
