"""Mesh construction and sharding specs for the placement scan.

The scale axes of this domain map onto a 2-D ``jax.sharding.Mesh``:

  "evals" — data parallelism over independent evaluations (each eval's scan
            is independent; the broker dequeues many at once). The analog of
            DP in an ML workload.
  "nodes" — model/sequence parallelism over the cluster's node axis: every
            [N]-shaped array (capacity, masks, scores) is sharded across
            chips, and XLA inserts the all-gather/all-reduce/argmax
            collectives the ring-ordered selection needs. The analog of
            TP/SP: the "long context" here is the 5K-node (and beyond)
            cluster state.

We use GSPMD via jit + NamedSharding rather than hand-written shard_map:
the scan body is dominated by elementwise ops, cumsums and reductions over
the node axis, all of which XLA partitions well.
"""
from __future__ import annotations

from typing import Optional, Tuple


def make_mesh(n_devices: Optional[int] = None, eval_parallel: int = 1):
    """Build a ("evals", "nodes") mesh over the available devices."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    ep = max(1, min(eval_parallel, n))
    while n % ep != 0:
        ep -= 1
    grid = np.asarray(devices).reshape(ep, n // ep)
    return Mesh(grid, ("evals", "nodes"))


def scan_input_shardings(mesh, batched: bool):
    """(static, carry, xs) PartitionSpecs for the placement scan.

    ``batched`` adds a leading eval axis (sharded over "evals") to carry/xs.
    Node-dim arrays shard over "nodes"; small per-TG tables replicate.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    b = ("evals",) if batched else ()

    static = (
        ns("nodes", None),        # totals [N, D]
        ns("nodes", None),        # reserved [N, D]
        ns(None, None),           # asks [G, D]
        ns(None, "nodes"),        # feas [G, N]
        ns(None, "nodes"),        # aff_score [G, N]
        ns(None, "nodes"),        # aff_present [G, N]
        ns(None),                 # desired_counts [G]
        ns(None),                 # dh_job [G]
        ns(None),                 # dh_tg [G]
        ns(None),                 # limits [G]
        ns(None, None, "nodes"),  # spread_vids [G, S, N]
        ns(None, None, None),     # spread_desired [G, S, V]
        ns(None, None),           # spread_weights [G, S]
        ns(None, None),           # spread_has_targets [G, S]
        ns(None, None),           # spread_active [G, S]
        ns(None),                 # sum_spread_weights [G]
        ns(),                     # n_real scalar
    )
    carry = (
        ns(*b, "nodes", None),    # used [N, D]
        ns(*b, None, "nodes"),    # tg_counts [G, N]
        ns(*b, "nodes"),          # job_counts [N]
        ns(*b, None, None, None),  # spread_counts [G, S, V]
        ns(*b, None, None, None),  # spread_entry [G, S, V]
        ns(*b),                   # offset
        ns(*b, None),             # failed [G]
    )
    xs = (
        ns(*b, None),             # tg_idx [P]
        ns(*b, None, None),       # penalty_idx [P, K]
        ns(*b, None),             # evict_node [P]
        ns(*b, None, None),       # evict_res [P, D]
        ns(*b, None),             # evict_tg [P]
        ns(*b, None),             # limit_p [P]
        ns(*b, None),             # sum_sw_p [P]
    )
    return static, carry, xs


def batched_place_scan(mesh, n_pad: int):
    """A jit'd, mesh-sharded, eval-batched placement scan.

    vmaps the single-eval scan over a leading batch axis (independent evals)
    and shards: batch over "evals", node axis over "nodes". Static (node
    table / TG spec) arrays are shared by all evals in the batch.
    """
    import jax

    from ..tpu.engine import _build_place_scan

    place_scan = _build_place_scan()

    static_s, carry_s, xs_s = scan_input_shardings(mesh, batched=True)

    def run(static, carry_b, xs_b):
        return jax.vmap(lambda c, x: place_scan(n_pad, static, c, x))(carry_b, xs_b)

    return jax.jit(run, in_shardings=(static_s, carry_s, xs_s))
