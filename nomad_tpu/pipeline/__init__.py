"""nomad-pipeline: the asynchronous eval-lifecycle pipeline.

The leader's placement path decomposes into explicit stages so
DIFFERENT eval waves occupy different stages at once — wave N+1's
encode overlaps wave N's device dispatch and wave N-1's raft commit,
instead of each eval traversing the whole chain serially on one worker
thread (the host-side convoy that capped r5's C1M run at ~514
placements/s around a ~94K/s device kernel):

    broker ──► worker: snapshot/encode ──► device dispatch ─┐
      ▲          (HOST_WORK_SEM,             (DeviceBatcher  │
      │           encode cache)               gather queue)  │
      │                                                      ▼
      │                              worker builds dense Plan│
      │                                 AsyncApplier.try_submit
      │                                          │
      │                              plan queue (bounded batch)
      │                                          │
      │                              Planner: evaluate (vectorized
      │                                numpy re-check) + batched
      │                                raft commit
      │                                          │
      │                              completion queue (bounded)
      │                                          │
      │            full commit: wait_min_index + ack
      └──────────┤
                   partial commit: re-dispatch from the wave's
                   remembered encode (row-subset + usage-epoch patch,
                   warm compile buckets) — else nack

Stages communicate ONLY through bounded queues (the broker's unack
table, the device batcher's gather queue, the plan queue's batch cap,
and this package's completion queue); the ``pipeline-stage-discipline``
lint rule keeps raft applies and state-store writes out of the
dispatch-stage thread. Per-stage spans (``encode`` / ``dispatch`` /
``evaluate`` / ``commit``, keyed by wave = eval id) land in
trace/lifecycle and surface as ``nomad.trace.pipeline.*`` gauges.

ServerConfig knobs: ``pipeline_async`` (master switch),
``pipeline_inflight`` (async waves in flight before workers fall back
to synchronous submit), ``pipeline_redispatch_max`` (device re-entries
per wave before nacking), ``pipeline_ack_timeout_s`` (watchdog bound on
an unacked accepted wave).
"""
from .applier import AsyncApplier
from .queues import BoundedStageQueue
from .redispatch import Redispatcher, WaveEncodeRegistry

__all__ = [
    "AsyncApplier",
    "BoundedStageQueue",
    "Redispatcher",
    "WaveEncodeRegistry",
]
