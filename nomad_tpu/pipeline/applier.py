"""Async applier: streams completed device waves into batched raft
entries off the dispatch thread, and hands the ack back to the broker.

The classic eval lifecycle parks the scheduler worker on the plan
future for the whole evaluate -> raft-commit tail, so a worker thread
can hold at most one wave in the pipeline at a time — at C1M scale the
fast device convoys behind the slow host tail. Here the worker hands a
device-built dense plan to ``try_submit`` and immediately returns to
the broker for the next eval; this applier owns the wave from plan
enqueue to broker ack:

  worker (dispatch stage)                 applier thread
    try_submit(plan, token) ──────────────► plan_queue.enqueue
      · pauses the broker nack timer          │ (Planner evaluates +
      · worker does NOT ack; returns          │  batches raft commits)
        to the broker immediately             ▼
                                          completion queue (bounded)
                                              │
                          full commit ◄───────┴──► partial commit
                              │                        │
                    wait_min_index(alloc_index)   redispatch (bounded
                              │                   attempts; cached
                        broker.ack                encode re-entry) or
                                                  broker.nack

Per-payload failure isolation comes from the Planner's batched waiter
(one raft entry per batch, per-payload error list from the FSM): a
poisoned wave resolves its OWN future with the error and is nacked
here; its batch-mates commit and ack normally. The watchdog sweep
bounds how long any accepted wave can sit unacked — ``ack_timeout_s``
after its last (re)enqueue it is force-nacked back to the broker, so a
stuck pipeline degrades to the classic retry path instead of
stranding evals.

Stage discipline (enforced by the ``pipeline-stage-discipline`` lint
rule): nothing in this package applies raft entries or writes the state
store directly — commits go through the plan queue, acks through the
broker, and stage handoff only through bounded queues.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..server.eval_broker import NotOutstandingError, TokenMismatchError
from ..server.raft import NotLeaderError
from ..structs.structs import Plan, PlanResult
from ..utils import metrics
from .queues import BoundedStageQueue
from .redispatch import Redispatcher, WaveEncodeRegistry
from ..utils.lock_witness import witness_lock
from ..utils.race_witness import tracked_dict, tracked_list

logger = logging.getLogger("nomad_tpu.pipeline.applier")


class _Wave:
    """One eval's dense plan in flight between submit and ack."""

    __slots__ = ("plan", "token", "attempts", "deadline", "not_before",
                 "done")

    def __init__(self, plan: Plan, token: str, deadline: float) -> None:
        self.plan = plan
        self.token = token
        self.attempts = 0
        self.deadline = deadline
        self.not_before = 0.0   # redispatch backoff gate (monotonic)
        self.done = False


class AsyncApplier:
    """Owns the evaluate/commit/ack tail of device-built dense plans.

    One instance per server; enabled only while leader (the plan queue
    and broker it drives are leader-only too). All state is bounded:
    ``inflight_max`` concurrent waves (a counting semaphore the worker
    polls non-blockingly — a full pipeline falls back to the classic
    synchronous submit, never queues unboundedly), one bounded
    completion queue, and a bounded per-wave redispatch budget.
    """

    def __init__(self, server, inflight_max: int = 128,
                 redispatch_max: int = 2,
                 ack_timeout_s: float = 30.0,
                 redispatch_backoff_s: float = 0.05,
                 redispatch_backoff_max_s: float = 1.0,
                 backpressure_wait_s: float = 0.02) -> None:
        self.server = server
        self.inflight_max = max(1, int(inflight_max))
        self.redispatch_max = max(0, int(redispatch_max))
        self.ack_timeout_s = float(ack_timeout_s)
        self.redispatch_backoff_s = max(0.0, float(redispatch_backoff_s))
        self.redispatch_backoff_max_s = max(
            self.redispatch_backoff_s, float(redispatch_backoff_max_s))
        self.backpressure_wait_s = max(0.0, float(backpressure_wait_s))

        self.registry = WaveEncodeRegistry()
        self.redispatcher = Redispatcher(server, self.registry)

        self._slots = threading.Semaphore(self.inflight_max)
        # every completion entry corresponds to a held slot, so the
        # queue can never actually fill past inflight_max — puts are
        # effectively non-blocking, the bound is the discipline
        self._completions = BoundedStageQueue(
            self.inflight_max + 1, name="wave-completions")
        self._lock = witness_lock("applier.AsyncApplier._lock")
        self._waves: Dict[str, _Wave] = tracked_dict(
            "applier.AsyncApplier._waves", {})
        # waves parked between redispatches (backoff); drained by _sweep
        self._deferred: List[_Wave] = tracked_list(
            "applier.AsyncApplier._deferred", [])
        self._enabled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        if enabled:
            with self._lock:
                if self._enabled:
                    return
                self._enabled = True
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pipeline-applier", daemon=True)
            self._thread.start()
        else:
            with self._lock:
                if not self._enabled:
                    return
                self._enabled = False
                waves = list(self._waves.values())
                self._deferred.clear()
            self._stop.set()
            # leadership is gone: the broker flush already closed the
            # unacks; just release the slots and drop the bookkeeping.
            # _mark_done arbitrates with a racing _finish so each slot
            # is released exactly once.
            for rec in waves:
                if self._mark_done(rec):
                    self._slots.release()
            self.registry.clear()
            t = self._thread
            self._thread = None
            if t is not None and t is not threading.current_thread():
                t.join(timeout=2.0)

    # -- dispatch-stage entry point (worker thread) ----------------------

    def try_submit(self, plan: Plan, token: str) -> bool:
        """Take ownership of a dense plan's commit + ack, or return False
        so the worker falls back to the classic synchronous submit.
        Called on the worker (dispatch-stage) thread; everything here is
        bounded — the longest wait is one ``backpressure_wait_s`` slot
        wait when the pipeline is full."""
        if not self._enabled or not getattr(plan, "async_ok", False):
            return False
        # async-eligible shape: device-built dense placements only. Any
        # object-path cargo (stops, preemptions, deployments,
        # annotations) keeps the worker's synchronous path, whose caller
        # inspects those results in ways a deferred commit can't honor.
        if (
            not plan.dense_placements
            or plan.node_allocation or plan.node_update
            or plan.node_preemptions
            or plan.deployment is not None or plan.deployment_updates
            or plan.annotations is not None
        ):
            return False
        if not self._slots.acquire(blocking=False):
            # explicit backpressure: the pipeline is full (an unblock
            # storm re-enqueued more waves than inflight_max). Defer with
            # one bounded wait for a slot instead of immediately falling
            # back — a transient spike degrades to a slightly-delayed
            # async submit; only sustained saturation convoys onto the
            # classic synchronous path below.
            metrics.incr_counter("nomad.pipeline.backpressure")
            if (self.backpressure_wait_s <= 0 or not self._slots.acquire(
                    timeout=self.backpressure_wait_s)):
                metrics.incr_counter("nomad.pipeline.slots_exhausted")
                return False
        try:
            # the broker must not redeliver while the wave sits in the
            # plan queue; the watchdog sweep below is the new bound
            self.server.eval_broker.pause_nack_timeout(plan.eval_id, token)
        except (NotOutstandingError, TokenMismatchError):
            self._slots.release()
            return False
        rec = _Wave(plan, token, time.monotonic() + self.ack_timeout_s)
        with self._lock:
            if not self._enabled:
                self._slots.release()
                return False
            self._waves[plan.eval_id] = rec
        if not self._enqueue(rec):
            if self._mark_done(rec):
                self._slots.release()
            return False
        metrics.incr_counter("nomad.pipeline.submitted")
        return True

    def remember_wave(self, eval_id: str, enc, job, node_epoch: int) -> None:
        """Engine hook: stash the wave's encode for possible re-dispatch
        (engine._pipeline_remember)."""
        if self._enabled:
            self.registry.remember(eval_id, enc, job, node_epoch)

    # -- applier thread --------------------------------------------------

    def _enqueue(self, rec: _Wave) -> bool:
        try:
            pending = self.server.plan_queue.enqueue(rec.plan)
        except Exception:  # noqa: BLE001 — queue disabled (leader churn)
            return False
        pending.future.add_done_callback(
            lambda fut, r=rec: self._completions.put((r, fut))
        )
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                rec, fut = self._completions.get(timeout=0.25)
            except Exception:  # queue.Empty
                self._sweep()
                continue
            try:
                self._handle(rec, fut)
            except Exception:  # noqa: BLE001 — never kill the applier
                logger.exception("wave handling failed")
                self._finish(rec, ack=False, why="handler_error")
            self._sweep()

    def _handle(self, rec: _Wave, fut) -> None:
        if rec.done:
            return  # watchdog or shutdown got here first
        try:
            result: PlanResult = fut.result()
        except NotLeaderError:
            # leadership lost mid-apply: this node can no longer commit
            # anything, so redispatching would only re-fail — or worse,
            # double-commit after the new leader reruns the eval. Nack
            # straight back (best-effort: the revoke-time broker flush may
            # already have closed the unack) and let the new leader's
            # eval restore redeliver the wave.
            metrics.incr_counter("nomad.pipeline.not_leader")
            self._finish(rec, ack=False, why="not_leader")
            return
        except Exception:  # noqa: BLE001 — per-payload FSM error
            metrics.incr_counter("nomad.pipeline.apply_error")
            self._finish(rec, ack=False, why="apply_error")
            return
        committed, expected, actual = result.full_commit(rec.plan)
        if committed:
            self._finish_ack(rec, result)
            return
        metrics.incr_counter("nomad.pipeline.partial_commit")
        logger.debug("partial commit for %s: attempted %d placed %d",
                     rec.plan.eval_id[:8], expected, actual)
        if rec.attempts >= self.redispatch_max:
            self._finish(rec, ack=False, why="redispatch_exhausted")
            return
        retry = None
        try:
            retry = self.redispatcher.build_retry(rec.plan, result)
        except Exception:  # noqa: BLE001
            logger.exception("redispatch failed for %s", rec.plan.eval_id[:8])
        if retry is None:
            self._finish(rec, ack=False, why="no_redispatch")
            return
        rec.plan = retry
        rec.attempts += 1
        # exponential backoff between redispatches: a flapping apply path
        # (OCC livelock, injected faults) degrades to spaced retries
        # instead of hot-looping device dispatches. The ack-timeout clock
        # restarts AFTER the backoff so the watchdog bound stays
        # per-attempt, not per-wave.
        delay = min(self.redispatch_backoff_s * (2 ** (rec.attempts - 1)),
                    self.redispatch_backoff_max_s)
        now = time.monotonic()
        rec.deadline = now + delay + self.ack_timeout_s
        if delay > 0:
            rec.not_before = now + delay
            metrics.incr_counter("nomad.pipeline.redispatch_deferred")
            with self._lock:
                if not self._enabled or rec.done:
                    return
                self._deferred.append(rec)
            return
        if not self._enqueue(rec):
            self._finish(rec, ack=False, why="queue_disabled")

    def _finish_ack(self, rec: _Wave, result: PlanResult) -> None:
        # wait-index handoff: the worker never blocked on this commit,
        # so make sure the local store observed the commit index before
        # the ack releases the next same-job eval to a worker that will
        # immediately snapshot
        idx = result.alloc_index or result.refresh_index
        if idx:
            try:
                self.server.fsm.state.wait_min_index(idx, timeout=5.0)
            except Exception:  # noqa: BLE001 — ack anyway; workers
                pass           # re-wait via shared_snapshot_min_index
        self._finish(rec, ack=True)

    def _mark_done(self, rec: _Wave) -> bool:
        """Exactly-once done transition, arbitrated under the lock. The
        caller that wins owns the wave's slot release / broker token —
        every other path (watchdog, shutdown, completion) loses the race
        cleanly instead of double-releasing."""
        with self._lock:
            if rec.done:
                return False
            rec.done = True
            self._waves.pop(rec.plan.eval_id, None)
            return True

    def _finish(self, rec: _Wave, ack: bool, why: str = "") -> None:
        if not self._mark_done(rec):
            return
        self.registry.forget(rec.plan.eval_id)
        broker = self.server.eval_broker
        try:
            if ack:
                broker.ack(rec.plan.eval_id, rec.token)
                metrics.incr_counter("nomad.pipeline.acked")
            else:
                broker.nack(rec.plan.eval_id, rec.token)
                metrics.incr_counter("nomad.pipeline.nacked")
                if why:
                    metrics.incr_counter(f"nomad.pipeline.nack.{why}")
        except (NotOutstandingError, TokenMismatchError):
            pass  # broker flushed (leader churn) or timer already fired
        except Exception:  # noqa: BLE001
            logger.exception("broker %s failed for %s",
                             "ack" if ack else "nack", rec.plan.eval_id[:8])
        finally:
            self._slots.release()

    def _sweep(self) -> None:
        """Watchdog + backoff pump: re-enqueue deferred redispatches whose
        backoff has elapsed, then force-nack any accepted wave sitting
        unacked past its deadline back to the broker's classic retry
        path. Runs at least every 0.25s (the completion-get timeout), so
        that is the effective backoff granularity."""
        now = time.monotonic()
        with self._lock:
            due = [r for r in self._deferred
                   if not r.done and r.not_before <= now]
            self._deferred[:] = [r for r in self._deferred
                                 if not r.done and r.not_before > now]
        for rec in due:
            if not self._enqueue(rec):
                self._finish(rec, ack=False, why="queue_disabled")
        with self._lock:
            overdue = [r for r in self._waves.values()
                       if not r.done and now > r.deadline]
        for rec in overdue:
            metrics.incr_counter("nomad.pipeline.watchdog_nack")
            logger.warning("wave %s unacked past %.1fs; force-nacking",
                           rec.plan.eval_id[:8], self.ack_timeout_s)
            self._finish(rec, ack=False, why="watchdog")

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            inflight = len(self._waves)
            deferred = len(self._deferred)
        out = {
            "inflight": inflight,
            "deferred": deferred,
            "completion_depth": self._completions.depth(),
            "encode_registry": len(self.registry),
            "slots_free": self.inflight_max - inflight,
        }
        batcher = getattr(self.server, "device_batcher", None)
        if batcher is not None:
            out["batcher_queue_depth"] = batcher.queue_depth()
        return out
