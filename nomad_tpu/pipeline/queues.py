"""Bounded stage-handoff queues for the eval-lifecycle pipeline.

Stage threads in ``nomad_tpu/pipeline`` may ONLY exchange work through
these queues (enforced by the ``pipeline-stage-discipline`` lint rule):
a bounded queue makes backpressure explicit — when the commit stage
falls behind, the dispatch stage blocks on a full queue instead of
growing an unbounded backlog that hides the stall until memory dies.
Depth is readable without locking the producer (``qsize`` is advisory,
which is all a gauge needs).
"""
from __future__ import annotations

import queue
from typing import Any, Optional


class BoundedStageQueue:
    """A bounded FIFO between two pipeline stages, with a depth gauge.

    Thin wrapper over ``queue.Queue`` on purpose: the value is the
    CONTRACT (bounded, depth-observable, the only legal stage handoff),
    not the mechanism.
    """

    def __init__(self, maxsize: int, name: str = "") -> None:
        if maxsize <= 0:
            raise ValueError("stage queues must be bounded (maxsize > 0)")
        self.name = name
        self.maxsize = maxsize
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=maxsize)

    def put(self, item: Any, timeout: Optional[float] = None) -> None:
        self._q.put(item, timeout=timeout)

    def put_nowait(self, item: Any) -> None:
        self._q.put_nowait(item)

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._q.get(timeout=timeout)

    def get_nowait(self) -> Any:
        return self._q.get_nowait()

    def depth(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()
