"""Retry-aware re-dispatch: partial OCC failures re-enter the device
stage from the failed wave's own encode.

When the async applier (pipeline/applier.py) sees a partial commit —
some of a wave's dense placements lost the optimistic-concurrency race
to capacity another wave grabbed first — the classic path nacks the
eval and the whole lifecycle replays: snapshot, reconcile, encode,
dispatch. But the failed wave's encode is already in hand (the engine
registers it here before dispatching, engine._pipeline_remember), and
every per-placement array in an ``EncodedEval.xs`` carries the
placement axis leading (encode.subset_encoded_rows), so the retry is:

  1. row-subset the encode to just the failed placements,
  2. patch the usage carry (carry[0]/carry[7]) to the CURRENT usage
     epoch via encode.epoch_usage_arrays — the same job-independent
     swap the whole-eval encode cache uses, so the retry sees exactly
     the capacity state that rejected it,
  3. re-dispatch through the batcher, padding into the coarse
     placement buckets that are already compile-warm from the first
     pass.

No snapshot, no reconcile, no encode — and no fresh ``encode`` stage
span, which is precisely what the OCC-storm test asserts.

Safety gates (bail to the broker-nack path, which is always correct):
the remembered encode must be dense-path (fresh placements only), free
of preemption/eviction state, free of distinct_hosts / distinct_property
constraints (their per-node counts in the carry would be stale after
the partial commit), 4-dim (the usage patch covers no device dims),
and the fleet must not have changed shape (node epoch).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from ..structs.structs import Plan, PlanResult
from ..trace import lifecycle as _lifecycle
from ..utils import metrics
from ..utils.lock_witness import witness_lock

logger = logging.getLogger("nomad_tpu.pipeline.redispatch")

# remembered encodes are references into arrays the engine already
# holds; the cap only bounds bookkeeping, not array memory
_REGISTRY_CAP = 512


class _ShimCtx:
    """The minimal EvalContext surface fleet_static/epoch_usage_arrays
    read: a state snapshot and the deterministic flag (remembered
    encodes only exist in deterministic mode — fleet_static returns
    None otherwise, and the engine's cache path requires a fleet)."""

    __slots__ = ("state", "deterministic")

    def __init__(self, state) -> None:
        self.state = state
        self.deterministic = True


class WaveEncodeRegistry:
    """eval id -> (encode, job, node_epoch) for waves currently in
    flight between device dispatch and raft commit. Bounded FIFO; the
    applier forgets entries on ack/nack."""

    def __init__(self, cap: int = _REGISTRY_CAP) -> None:
        self._lock = witness_lock("redispatch.WaveEncodeRegistry._lock")
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self.cap = cap

    def remember(self, eval_id: str, enc, job, node_epoch: int) -> None:
        with self._lock:
            self._entries.pop(eval_id, None)
            self._entries[eval_id] = (enc, job, node_epoch)
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)

    def get(self, eval_id: str) -> Optional[tuple]:
        with self._lock:
            return self._entries.get(eval_id)

    def forget(self, eval_id: str) -> None:
        with self._lock:
            self._entries.pop(eval_id, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def _retry_eligible(enc) -> Optional[str]:
    """None when the remembered encode can be row-subset + usage-patched
    safely; else the reason it can't."""
    if not enc.dense_ok:
        return "not dense"
    if enc.pre_allocs is not None:
        return "preemption tables"
    static = enc.static
    if static[0].shape[1] != 4:
        return "device dims"
    # distinct_hosts / distinct_property counts in the carry are stale
    # once part of the wave committed
    if bool(np.asarray(static[6]).any()) or bool(np.asarray(static[7]).any()):
        return "distinct_hosts"
    if static[17].shape[0] > 0:
        return "distinct_property"
    # spread bucket counts are wave-relative state too
    if bool(np.asarray(static[13]).any()):
        return "spread"
    # eviction steps must be absent (no destructive placements rode
    # along); evict_node is (p,) with -1 = no eviction for that row
    if bool((np.asarray(enc.xs[2]) >= 0).any()):
        return "eviction axis"
    # forced-node (system path) encodes carry a non-empty width axis
    if enc.xs[9].ndim == 2 and enc.xs[9].shape[1] > 0:
        return "forced nodes"
    return None


class Redispatcher:
    """Builds the retry plan for a partially-committed wave, or returns
    None when the safe answer is the classic nack path."""

    def __init__(self, server, registry: WaveEncodeRegistry) -> None:
        self.server = server
        self.registry = registry

    # -- failed-placement mapping ---------------------------------------

    @staticmethod
    def _failed_keys(plan: Plan, result: PlanResult) -> List[Tuple[str, str]]:
        """(task_group, placement name) of every planned dense placement
        the applier did NOT commit."""
        committed = {
            i for b in result.dense_placements for i in b.ids
        }
        failed: List[Tuple[str, str]] = []
        for block in plan.dense_placements:
            for i, pid in enumerate(block.ids):
                if pid not in committed:
                    failed.append((block.task_group, block.names[i]))
        return failed

    # -- retry construction ---------------------------------------------

    def build_retry(self, plan: Plan, result: PlanResult) -> Optional[Plan]:
        rec = self.registry.get(plan.eval_id)
        if rec is None:
            metrics.incr_counter("nomad.pipeline.redispatch_miss")
            return None
        enc, job, node_epoch = rec

        reason = _retry_eligible(enc)
        if reason is not None:
            logger.debug("redispatch ineligible (%s): %s", plan.eval_id[:8],
                         reason)
            metrics.incr_counter("nomad.pipeline.redispatch_ineligible")
            return None

        snap = self.server.fsm.state.snapshot()
        if getattr(snap, "node_epoch", -1) != node_epoch:
            metrics.incr_counter("nomad.pipeline.redispatch_node_epoch")
            return None

        failed = self._failed_keys(plan, result)
        if not failed:
            return None
        failed_set = set(failed)
        rows = [
            k for k, m in enumerate(enc.missing_list)
            if (m.get_task_group().name, m.get_name()) in failed_set
        ]
        if len(rows) != len(failed):
            # the plan's placements don't map 1:1 onto the remembered
            # encode (shouldn't happen; refuse rather than guess)
            metrics.incr_counter("nomad.pipeline.redispatch_map_mismatch")
            return None

        retry_enc = self._patched_subset(enc, job, snap, rows)
        if retry_enc is None:
            return None

        from ..tpu.engine import TpuPlacementEngine

        engine = TpuPlacementEngine.shared()
        batcher = self.server.device_batcher
        with _lifecycle.pipeline_stage("dispatch", plan.eval_id):
            if batcher is not None:
                chosen, scores, pulls, skipped, _evict = batcher.run(retry_enc)
            else:
                chosen, scores, pulls, skipped, _evict = engine.run_scan_single(
                    retry_enc)
        p = retry_enc.p
        chosen = np.asarray(chosen)[:p]
        skipped = np.asarray(skipped)[:p]
        if (chosen < 0).any() or skipped.any():
            # capacity genuinely gone — a fresh eval pass (blocked-eval
            # machinery included) must decide, not a blind retry
            metrics.incr_counter("nomad.pipeline.redispatch_unplaced")
            return None

        blocks = self._dense_blocks(plan, job, retry_enc, chosen,
                                    np.asarray(scores)[:p],
                                    np.asarray(pulls)[:p])
        metrics.incr_counter("nomad.pipeline.redispatch")
        metrics.incr_counter("nomad.pipeline.redispatch_encode_reuse")
        return Plan(
            eval_id=plan.eval_id,
            eval_token=plan.eval_token,
            priority=plan.priority,
            all_at_once=plan.all_at_once,
            job=plan.job,
            dense_placements=blocks,
            snapshot_index=snap.latest_index,
            async_ok=True,
        )

    def _patched_subset(self, enc, job, snap, rows):
        """Row-subset the encode and swap its usage arrays to the
        snapshot's epoch (the encode-cache patch, reused)."""
        from ..tpu.encode import (
            epoch_usage_arrays,
            fleet_static,
            subset_encoded_rows,
        )
        from ..tpu.engine import EncodedEval

        ctx = _ShimCtx(snap)
        fleet = fleet_static(ctx, job, enc.nodes)
        if fleet is None:
            metrics.incr_counter("nomad.pipeline.redispatch_no_fleet")
            return None
        try:
            used0, e_base0 = epoch_usage_arrays(
                ctx, fleet, enc.n_pad, enc.dtype == np.int32, enc.dtype
            )
        except Exception:  # noqa: BLE001 — patch failure => classic path
            logger.exception("usage patch failed for redispatch")
            return None
        carry = list(enc.carry)
        carry[0] = used0
        carry[7] = e_base0
        xs_sub, ml_sub = subset_encoded_rows(enc.xs, enc.missing_list, rows)
        return EncodedEval(
            n_real=enc.n_real, n_pad=enc.n_pad, g=enc.g, s=enc.s, v=enc.v,
            p=len(rows), dtype=enc.dtype, static=enc.static,
            carry=tuple(carry), xs=xs_sub, missing_list=ml_sub,
            nodes=enc.nodes, table=enc.table,
            start_ns=time.monotonic_ns(), dense_ok=True,
        )

    @staticmethod
    def _dense_blocks(plan: Plan, job, enc, chosen, scores, pulls):
        """Committed-shape DenseTGPlacements for the retry results,
        grouped by task group (engine._apply_results_dense, minus the
        scheduler context)."""
        from ..tpu.engine import TpuPlacementEngine

        dep_by_tg = {b.task_group: b.deployment_id
                     for b in plan.dense_placements}
        scores_f = TpuPlacementEngine._scores_to_float(np.asarray(scores))
        tg_idx = enc.xs[0]
        blocks = []
        for gi in np.unique(tg_idx):
            sel = np.nonzero(tg_idx == gi)[0]
            tg = job.task_groups[int(gi)]
            blocks.append(TpuPlacementEngine._dense_block(
                job, tg, plan.eval_id,
                chosen[sel], enc.nodes,
                names=[enc.missing_list[int(k)].get_name() for k in sel],
                scores_f=scores_f[sel],
                nodes_evaluated=np.asarray(pulls)[sel].tolist(),
                nodes_available={},
                deployment_id=dep_by_tg.get(tg.name, ""),
            ))
        return blocks
