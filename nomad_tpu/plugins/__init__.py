"""Plugin system: out-of-process driver/device plugins.

Fills the role of the reference's go-plugin stack (``plugins/base``,
``plugins/drivers``, ``plugins/device``, ``helper/pluginutils``): plugins
run as subprocesses serving the driver/device protocol over a unix-domain
socket with the same msgpack framing the server RPC uses (the gRPC slot),
discovered and launched by a catalog.
"""
from .base import API_VERSION, PluginInfo
from .catalog import Catalog, register_external_driver
from .device import ContainerReservation, DeviceGroup, DevicePlugin

__all__ = [
    "API_VERSION",
    "PluginInfo",
    "Catalog",
    "register_external_driver",
    "DevicePlugin",
    "DeviceGroup",
    "ContainerReservation",
]
