"""Base plugin protocol (reference ``plugins/base/proto/base.proto``).

Every plugin — driver or device — answers ``PluginInfo``, exposes a config
schema, and accepts ``SetConfig`` before use. The schema is a plain
declarative dict (the hclspec slot, plugins/shared/hclspec): attribute name
→ {"type": ..., "required": ..., "default": ...}; agents validate plugin
stanzas against it without importing the plugin.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

API_VERSION = "v0.1.0"
PLUGIN_TYPE_DRIVER = "driver"
PLUGIN_TYPE_DEVICE = "device"

# stdout handshake line the subprocess prints once its socket is live
# (go-plugin's "CORE-PROTOCOL-VERSION|APP-PROTOCOL-VERSION|NETWORK|ADDR|PROTOCOL")
HANDSHAKE_PREFIX = "NOMAD-TPU-PLUGIN|1|"


@dataclass
class PluginInfo:
    type: str = PLUGIN_TYPE_DRIVER
    name: str = ""
    plugin_version: str = "0.1.0"
    plugin_api_versions: tuple = (API_VERSION,)


class BasePlugin:
    """Implemented by every plugin object served over the socket."""

    def plugin_info(self) -> PluginInfo:
        raise NotImplementedError

    def config_schema(self) -> Dict[str, Any]:
        return {}

    def set_config(self, config: Dict[str, Any]) -> None:
        self.config = dict(config)


def validate_config(schema: Dict[str, Any], config: Dict[str, Any]) -> list:
    """Schema-check a plugin config stanza; returns error strings.

    Schemas are hclspec-style schema-as-data trees (plugins/hclspec.py —
    the reference's plugins/shared/hclspec protocol); the legacy flat
    ``{key: {"type", "required"}}`` form upgrades transparently."""
    from .hclspec import decode

    _, errors = decode(schema, config or {})
    return errors


def decode_config(schema: Dict[str, Any], config: Dict[str, Any]):
    """Validate AND default-apply: (decoded_config, errors)."""
    from .hclspec import decode

    return decode(schema, config or {})
