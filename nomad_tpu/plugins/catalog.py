"""Plugin catalog: built-in registry + external discovery/launch.

Fills the role of reference ``helper/pluginutils/catalog`` (register.go
built-ins) + ``helper/pluginutils/loader`` (external plugin discovery from
plugin_dir, config validation, instance caching): built-in drivers stay
in-process by default; anything in ``plugin_dir`` (executables named
``nomad-driver-*`` / ``nomad-device-*``) or registered via
``register_external_driver`` runs as a subprocess, one shared instance per
plugin name.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Dict, List, Optional

from .base import PLUGIN_TYPE_DEVICE, PLUGIN_TYPE_DRIVER, validate_config
from .device import ExternalDevicePlugin
from .driver_plugin import ExternalDriver
from .transport import PluginError, spawn_plugin

logger = logging.getLogger("nomad_tpu.plugins.catalog")

_lock = threading.Lock()
_external_instances: Dict[str, object] = {}


def _plugin_env() -> dict:
    """Subprocess env: make the framework importable from the repo root."""
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return env


def launch_builtin_driver(name: str) -> ExternalDriver:
    """Run a BUILT-IN driver out-of-process (the reference's default mode:
    every driver is a go-plugin subprocess)."""
    argv = [sys.executable, "-m", "nomad_tpu.plugins.launch", "driver", name]
    client = spawn_plugin(argv, env=_plugin_env())
    return ExternalDriver(name, client)


def launch_external(path: str) -> object:
    """Launch a discovered plugin executable; returns ExternalDriver or
    ExternalDevicePlugin based on its self-reported plugin_info."""
    client = spawn_plugin([path], env=_plugin_env())
    info = client.call("plugin_info", timeout=10.0)
    if info.type == PLUGIN_TYPE_DRIVER:
        return ExternalDriver(info.name, client)
    if info.type == PLUGIN_TYPE_DEVICE:
        return ExternalDevicePlugin(info.name, client)
    client.close()
    raise PluginError(f"plugin {path} has unknown type {info.type!r}")


_replaced_factories: Dict[str, object] = {}


def register_external_driver(name: str, config: Optional[dict] = None) -> None:
    """Re-register a built-in driver name to run out-of-process: callers
    of ``new_driver(name)`` transparently get the shared subprocess-backed
    instance. ``close_external_driver`` undoes this."""
    from ..client.drivers.base import register

    def factory():
        with _lock:
            inst = _external_instances.get(name)
            if inst is not None and inst.client.alive():
                return inst
            inst = launch_builtin_driver(name)
            if config:
                schema = inst.config_schema()
                errors = validate_config(schema, config) if schema else []
                if errors:
                    inst.close()
                    raise PluginError("; ".join(errors))
                inst.set_config(config)
            _external_instances[name] = inst
            return inst

    prior = register(name, factory)
    with _lock:
        _replaced_factories.setdefault(name, prior)


def close_external_driver(name: str) -> None:
    """Stop the shared subprocess for ``name`` and reinstate whatever
    factory it displaced (typically the in-process built-in)."""
    from ..client.drivers.base import restore

    with _lock:
        inst = _external_instances.pop(name, None)
        prior = _replaced_factories.pop(name, None)
    if inst is not None:
        try:
            inst.close()
        except Exception:  # noqa: BLE001
            pass
    restore(name, prior)


class Catalog:
    """Discovers and owns external plugin instances for one agent."""

    def __init__(self, plugin_dir: str = "") -> None:
        self.plugin_dir = plugin_dir
        self.drivers: Dict[str, ExternalDriver] = {}
        self.devices: Dict[str, ExternalDevicePlugin] = {}
        self._displaced: Dict[str, object] = {}  # name → prior factory

    def discover(self) -> "Catalog":
        """Scan plugin_dir for plugin executables (loader discovery)."""
        if not self.plugin_dir or not os.path.isdir(self.plugin_dir):
            return self
        for entry in sorted(os.listdir(self.plugin_dir)):
            path = os.path.join(self.plugin_dir, entry)
            if not (os.path.isfile(path) and os.access(path, os.X_OK)):
                continue
            if not entry.startswith(("nomad-driver-", "nomad-device-")):
                continue
            try:
                plugin = launch_external(path)
            except (PluginError, OSError) as e:
                # one malformed executable (bad shebang, wrong arch) must
                # not take the node agent down
                logger.warning("failed to launch plugin %s: %s", path, e)
                continue
            if isinstance(plugin, ExternalDriver):
                self.drivers[plugin.name] = plugin
                from ..client.drivers.base import register

                prior = register(plugin.name, lambda p=plugin: p)
                self._displaced.setdefault(plugin.name, prior)
            else:
                self.devices[plugin.name] = plugin
        return self

    def close(self) -> None:
        from ..client.drivers.base import restore

        for name, d in list(self.drivers.items()):
            d.close()
            restore(name, self._displaced.pop(name, None))
        for d in list(self.devices.values()):
            d.close()
        self.drivers.clear()
        self.devices.clear()


def shutdown_external_instances() -> None:
    """Stop every shared subprocess driver and restore displaced
    factories."""
    with _lock:
        names = set(_external_instances) | set(_replaced_factories)
    for name in names:
        close_external_driver(name)
