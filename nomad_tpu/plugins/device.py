"""Device plugin protocol (reference ``plugins/device/device.go:20``).

A device plugin fingerprints groups of schedulable devices
(vendor/type/name + attributes), reserves instances for a task (returning
env vars + mounts, device.go Reserve → ContainerReservation), and reports
per-instance stats. ``DevicePluginShim``/``ExternalDevicePlugin`` mirror
the driver plugin split over the same transport.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .base import PLUGIN_TYPE_DEVICE, BasePlugin, PluginInfo
from .transport import PluginClient, PluginError


@dataclass
class DetectedDevice:
    """One device instance (device.go Device)."""

    id: str = ""
    healthy: bool = True
    health_description: str = ""


@dataclass
class DeviceGroup:
    """Homogeneous device group (device.go DeviceGroup): the unit the
    scheduler matches constraints/affinities against."""

    vendor: str = ""
    type: str = ""
    name: str = ""
    devices: List[DetectedDevice] = field(default_factory=list)
    attributes: Dict[str, str] = field(default_factory=dict)


@dataclass
class Mount:
    task_path: str = ""
    host_path: str = ""
    read_only: bool = True


@dataclass
class DeviceSpec:
    task_path: str = ""
    host_path: str = ""
    permissions: str = "rwm"


@dataclass
class ContainerReservation:
    """What a task gets for its reserved devices (device.go
    ContainerReservation)."""

    envs: Dict[str, str] = field(default_factory=dict)
    mounts: List[Mount] = field(default_factory=list)
    devices: List[DeviceSpec] = field(default_factory=list)


@dataclass
class DeviceStats:
    instance_stats: Dict[str, Dict[str, float]] = field(default_factory=dict)
    timestamp_ns: int = 0


class DevicePlugin(BasePlugin):
    """Concrete device plugins implement fingerprint/reserve/stats."""

    name = "device"

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(type=PLUGIN_TYPE_DEVICE, name=self.name)

    def fingerprint(self) -> List[DeviceGroup]:
        raise NotImplementedError

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        raise NotImplementedError

    def stats(self) -> DeviceStats:
        return DeviceStats(timestamp_ns=time.time_ns())


class DevicePluginShim(BasePlugin):
    """Subprocess side."""

    def __init__(self, plugin: DevicePlugin) -> None:
        self.plugin = plugin

    def plugin_info(self) -> PluginInfo:
        return self.plugin.plugin_info()

    def config_schema(self):
        return self.plugin.config_schema()

    def set_config(self, config) -> None:
        self.plugin.set_config(config)

    def fingerprint(self) -> List[DeviceGroup]:
        return self.plugin.fingerprint()

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        return self.plugin.reserve(device_ids)

    def stats(self) -> DeviceStats:
        return self.plugin.stats()


class ExternalDevicePlugin(DevicePlugin):
    """Agent side: device plugin behind a subprocess boundary."""

    def __init__(self, name: str, client: PluginClient) -> None:
        self.name = name
        self.client = client

    def fingerprint(self) -> List[DeviceGroup]:
        return self.client.call("fingerprint", timeout=30.0)

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        return self.client.call("reserve", device_ids, timeout=30.0)

    def stats(self) -> DeviceStats:
        return self.client.call("stats", timeout=30.0)

    def close(self) -> None:
        self.client.close()
