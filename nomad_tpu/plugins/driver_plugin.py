"""Driver plugin protocol: serve / consume a task driver over the plugin
transport.

Fills the role of reference ``plugins/drivers`` (driver.go:40 DriverPlugin,
client.go gRPC client, server.go gRPC server): ``DriverPluginShim`` is the
subprocess side wrapping a concrete ``Driver``; ``ExternalDriver`` is the
agent side — a ``Driver`` whose every method crosses the process boundary,
so the task runner and fingerprinter run unchanged against in-process and
out-of-process drivers alike.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..client.drivers.base import (
    Capabilities,
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStats,
    TaskStatus,
)
from .base import PLUGIN_TYPE_DRIVER, BasePlugin, PluginInfo
from .transport import PluginClient, PluginError


class DriverPluginShim(BasePlugin):
    """Subprocess side: exposes a concrete Driver over the socket."""

    def __init__(self, driver: Driver) -> None:
        self.driver = driver

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(type=PLUGIN_TYPE_DRIVER, name=self.driver.name)

    def config_schema(self) -> Dict[str, Any]:
        return getattr(self.driver, "config_schema", {})

    def set_config(self, config: Dict[str, Any]) -> None:
        setter = getattr(self.driver, "set_config", None)
        if setter is not None:
            setter(config)

    def capabilities(self) -> Capabilities:
        return self.driver.capabilities

    def produces_logs(self) -> bool:
        return bool(getattr(self.driver, "produces_logs", False))

    def fingerprint(self) -> Fingerprint:
        return self.driver.fingerprint()

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        return self.driver.start_task(cfg)

    def wait_task(self, task_id: str, timeout: Optional[float] = None):
        return self.driver.wait_task(task_id, timeout)

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        self.driver.stop_task(task_id, timeout_s, signal)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        self.driver.destroy_task(task_id, force)

    def inspect_task(self, task_id: str) -> TaskStatus:
        return self.driver.inspect_task(task_id)

    def task_stats(self, task_id: str) -> TaskStats:
        return self.driver.task_stats(task_id)

    def recover_task(self, handle: TaskHandle) -> None:
        self.driver.recover_task(handle)

    def signal_task(self, task_id: str, signal: str) -> None:
        self.driver.signal_task(task_id, signal)

    def exec_task(self, task_id: str, cmd: List[str], timeout_s: float):
        return self.driver.exec_task(task_id, cmd, timeout_s)


class ExternalDriver(Driver):
    """Agent side: a Driver backed by a plugin subprocess. One instance
    (and one subprocess) is shared by every task using the driver —
    the reference's drivermanager holds one plugin instance per driver."""

    def __init__(self, name: str, client: PluginClient) -> None:
        self.name = name
        self.client = client
        try:
            self.capabilities = client.call("capabilities", timeout=10.0)
        except PluginError:
            self.capabilities = Capabilities()
        try:
            self.produces_logs = client.call("produces_logs", timeout=10.0)
        except PluginError:
            # older plugin without the method: don't clobber capabilities
            self.produces_logs = False

    def _call(self, method: str, *args, timeout: Optional[float] = None):
        try:
            return self.client.call(method, *args, timeout=timeout)
        except PluginError as e:
            raise DriverError(str(e)) from e

    def plugin_info(self) -> PluginInfo:
        return self._call("plugin_info", timeout=10.0)

    def config_schema(self) -> Dict[str, Any]:
        return self._call("config_schema", timeout=10.0)

    def set_config(self, config: Dict[str, Any]) -> None:
        self._call("set_config", config, timeout=10.0)

    def fingerprint(self) -> Fingerprint:
        try:
            return self._call("fingerprint", timeout=10.0)
        except DriverError as e:
            from ..client.drivers.base import HEALTH_UNDETECTED

            return Fingerprint(health=HEALTH_UNDETECTED, health_description=str(e))

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        return self._call("start_task", cfg, timeout=60.0)

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        # socket timeout must outlast the server-side wait
        sock_timeout = None if timeout is None else timeout + 10.0
        return self._call("wait_task", task_id, timeout, timeout=sock_timeout)

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        self._call("stop_task", task_id, timeout_s, signal, timeout=timeout_s + 30.0)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        self._call("destroy_task", task_id, force, timeout=30.0)

    def inspect_task(self, task_id: str) -> TaskStatus:
        return self._call("inspect_task", task_id, timeout=10.0)

    def task_stats(self, task_id: str) -> TaskStats:
        return self._call("task_stats", task_id, timeout=10.0)

    def recover_task(self, handle: TaskHandle) -> None:
        self._call("recover_task", handle, timeout=30.0)

    def signal_task(self, task_id: str, signal: str) -> None:
        self._call("signal_task", task_id, signal, timeout=10.0)

    def exec_task(self, task_id: str, cmd: List[str], timeout_s: float) -> Tuple[bytes, int]:
        out = self._call("exec_task", task_id, cmd, timeout_s, timeout=timeout_s + 30.0)
        data, code = out
        return bytes(data), code

    def close(self) -> None:
        self.client.close()
