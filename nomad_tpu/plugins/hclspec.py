"""hclspec — schema-as-data for plugin configuration (reference
plugins/shared/hclspec/hcl_spec.proto:1-50).

The reference ships plugin config schemas as protobuf Spec trees that
the agent uses to decode/validate a driver's HCL config. This module is
the same idea with plain dicts as the wire format (the plugin transport
is msgpack here, so schema-as-data needs no extra codegen):

  {"attr":    {"type": "string"|"number"|"bool"|"list(string)"|...,
               "required": bool}}
  {"block":   {"spec": {field: Spec, ...}}}
  {"block_list": {"spec": {...}}}          # repeated blocks
  {"default": {"primary": Spec, "default": value}}
  {"literal": {"value": value}}

``decode(spec, value)`` validates ``value`` against the spec, applies
defaults, and returns (decoded, errors). A plugin's ``config_schema()``
may return either this spec form or the legacy flat
``{key: {"type", "required"}}`` form, which is auto-upgraded.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

_PRIMS = {
    "string": str,
    "number": (int, float),
    "int": int,
    "float": (int, float),
    "bool": bool,
    "any": object,
}


class SpecError(Exception):
    pass


def upgrade_flat_schema(flat: Dict[str, Any]) -> Dict[str, Any]:
    """Legacy flat {key: {"type", "required", "default"}} → block spec."""
    fields: Dict[str, Any] = {}
    for key, meta in flat.items():
        t = meta.get("type", "any")
        if t == "list":
            t = "list(any)"
        elif t == "map":
            t = "map(any)"
        attr = {"attr": {"type": t, "required": bool(meta.get("required"))}}
        if "default" in meta:
            attr = {"default": {"primary": attr, "default": meta["default"]}}
        fields[key] = attr
    return {"block": {"spec": fields}}


def normalize(schema: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Accept either a spec tree or the legacy flat schema."""
    if not schema:
        return {"block": {"spec": {}}}
    if any(k in schema for k in ("attr", "block", "block_list", "default", "literal")):
        return schema
    return upgrade_flat_schema(schema)


def _check_type(path: str, t: str, value: Any, errors: List[str]) -> Any:
    if t.startswith("list(") and t.endswith(")"):
        if not isinstance(value, list):
            errors.append(f"{path}: expected list, got {type(value).__name__}")
            return value
        inner = t[5:-1]
        return [_check_type(f"{path}[{i}]", inner, v, errors)
                for i, v in enumerate(value)]
    if t.startswith("map(") and t.endswith(")"):
        if not isinstance(value, dict):
            errors.append(f"{path}: expected map, got {type(value).__name__}")
            return value
        inner = t[4:-1]
        return {k: _check_type(f"{path}.{k}", inner, v, errors)
                for k, v in value.items()}
    want = _PRIMS.get(t)
    if want is None:
        errors.append(f"{path}: unknown spec type {t!r}")
        return value
    if want is object:
        return value
    # bool is an int subclass: don't admit True for a number attr
    if isinstance(value, bool) and want is not bool and t != "any":
        errors.append(f"{path}: expected {t}, got bool")
        return value
    if not isinstance(value, want):
        errors.append(f"{path}: expected {t}, got {type(value).__name__}")
    return value


def _decode(spec: Dict[str, Any], value: Any, path: str,
            errors: List[str]) -> Any:
    if "literal" in spec:
        return spec["literal"].get("value")
    if "default" in spec:
        node = spec["default"]
        if value is None:
            return node.get("default")
        return _decode(node["primary"], value, path, errors)
    if "attr" in spec:
        node = spec["attr"]
        if value is None:
            if node.get("required"):
                errors.append(f"{path}: required attribute missing")
            return None
        return _check_type(path, node.get("type", "any"), value, errors)
    if "block" in spec:
        fields = spec["block"].get("spec", {})
        if value is None:
            value = {}
        if not isinstance(value, dict):
            errors.append(f"{path}: expected block, got {type(value).__name__}")
            return value
        out = {}
        for key, sub in fields.items():
            out_val = _decode(sub, value.get(key), f"{path}.{key}" if path else key,
                              errors)
            if out_val is not None or key in value:
                out[key] = out_val
        for key in value:
            if key not in fields:
                errors.append(f"{path + '.' if path else ''}{key}: unknown field")
        return out
    if "block_list" in spec:
        inner = {"block": {"spec": spec["block_list"].get("spec", {})}}
        if value is None:
            return []
        if not isinstance(value, list):
            errors.append(f"{path}: expected list of blocks")
            return value
        return [_decode(inner, v, f"{path}[{i}]", errors)
                for i, v in enumerate(value)]
    errors.append(f"{path}: malformed spec node {sorted(spec)}")
    return value


def decode(schema: Optional[Dict[str, Any]], value: Any) -> Tuple[Any, List[str]]:
    """Validate + default-apply ``value`` against ``schema``.
    Returns (decoded, errors); errors empty on success."""
    errors: List[str] = []
    decoded = _decode(normalize(schema), value, "", errors)
    return decoded, errors
