"""Plugin subprocess entrypoint.

``python -m nomad_tpu.plugins.launch driver <name>`` serves a built-in
driver out-of-process; ``... device <module>:<attr>`` serves a device
plugin factory. External plugin executables are free to call
``transport.serve_main`` themselves — this module is the built-in shim
(the role of the reference's plugin main() + go-plugin Serve).
"""
from __future__ import annotations

import importlib
import sys


def main(argv) -> int:
    if len(argv) < 2:
        print("usage: launch driver <name> | device <module>:<attr>", file=sys.stderr)
        return 2
    kind, target = argv[0], argv[1]
    if kind == "driver":
        from ..client.drivers import new_driver  # package import registers built-ins
        from .driver_plugin import DriverPluginShim
        from .transport import serve_main

        serve_main(DriverPluginShim(new_driver(target)))
    elif kind == "device":
        module_name, _, attr = target.partition(":")
        module = importlib.import_module(module_name)
        factory = getattr(module, attr or "plugin")
        from .device import DevicePluginShim
        from .transport import serve_main

        serve_main(DevicePluginShim(factory()))
    else:
        print(f"unknown plugin kind {kind!r}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
