"""Scriptable mock device plugin (reference ``plugins/device/mock.go``):
fingerprints a configurable group of fake devices and reserves them with
deterministic env vars — the test double for the device manager.
"""
from __future__ import annotations

import time
from typing import List

from .device import (
    ContainerReservation,
    DetectedDevice,
    DeviceGroup,
    DevicePlugin,
    DeviceStats,
)


class MockDevicePlugin(DevicePlugin):
    name = "mock-device"
    config_schema_spec = {
        "vendor": {"type": "string"},
        "model": {"type": "string"},
        "count": {"type": "int"},
    }

    def __init__(self, vendor: str = "nomad", model: str = "mock", count: int = 2):
        self.vendor = vendor
        self.model = model
        self.count = count
        self.config = {}

    def config_schema(self):
        return self.config_schema_spec

    def set_config(self, config) -> None:
        self.config = dict(config)
        self.vendor = config.get("vendor", self.vendor)
        self.model = config.get("model", self.model)
        self.count = int(config.get("count", self.count))

    def fingerprint(self) -> List[DeviceGroup]:
        return [
            DeviceGroup(
                vendor=self.vendor,
                type="gpu",
                name=self.model,
                devices=[
                    DetectedDevice(id=f"{self.model}-{i}") for i in range(self.count)
                ],
                attributes={"memory_mib": "4096"},
            )
        ]

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        known = {f"{self.model}-{i}" for i in range(self.count)}
        for did in device_ids:
            if did not in known:
                raise ValueError(f"unknown device {did!r}")
        return ContainerReservation(
            envs={"MOCK_VISIBLE_DEVICES": ",".join(sorted(device_ids))}
        )

    def stats(self) -> DeviceStats:
        return DeviceStats(
            instance_stats={
                f"{self.model}-{i}": {"utilization": 0.0} for i in range(self.count)
            },
            timestamp_ns=time.time_ns(),
        )


def plugin() -> MockDevicePlugin:
    return MockDevicePlugin()
