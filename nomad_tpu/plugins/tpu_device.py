"""TPU device plugin.

Fills the nvidia-device-plugin slot (reference ``devices/gpu/nvidia/``:
NVML fingerprint → device groups, Reserve → ``NVIDIA_VISIBLE_DEVICES``)
for the hardware this framework targets: fingerprints the host's TPU
chips through JAX (the NVML analog), exposes them as a schedulable device
group, and reserves instances by exporting ``TPU_VISIBLE_CHIPS`` /
``JAX_PLATFORMS`` so the task's JAX runtime binds only its assigned chips.
Degrades to no-devices on hosts without TPUs (nvidia fingerprint.go does
the same when NVML is absent).
"""
from __future__ import annotations

import time
from typing import List

from .device import (
    ContainerReservation,
    DetectedDevice,
    DeviceGroup,
    DevicePlugin,
    DeviceStats,
)


class TPUDevicePlugin(DevicePlugin):
    name = "tpu"
    config_schema_spec = {
        "platform": {"type": "string"},  # override auto-detection ("tpu")
    }

    def __init__(self) -> None:
        self.config = {}

    def config_schema(self):
        return self.config_schema_spec

    def _detect(self) -> List[DeviceGroup]:
        try:
            import jax

            platform = self.config.get("platform", "")
            devices = (
                jax.devices(platform) if platform else jax.devices()
            )
        except Exception:  # noqa: BLE001 — no TPU runtime on this host
            return []
        groups = {}
        for d in devices:
            kind = getattr(d, "device_kind", "unknown")
            g = groups.get(kind)
            if g is None:
                g = groups[kind] = DeviceGroup(
                    vendor="google",
                    type=getattr(d, "platform", "tpu"),
                    name=kind,
                    attributes={},
                )
            g.devices.append(DetectedDevice(id=str(d.id)))
        for g in groups.values():
            g.attributes["count"] = str(len(g.devices))
        return list(groups.values())

    def fingerprint(self) -> List[DeviceGroup]:
        # no memoization: the device manager's periodic pass must see
        # chips appear (runtime comes up late) or go unhealthy
        return self._detect()

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        known = {d.id for g in self.fingerprint() for d in g.devices}
        for did in device_ids:
            if did not in known:
                raise ValueError(f"unknown TPU chip {did!r}")
        chips = ",".join(sorted(device_ids, key=lambda x: int(x) if x.isdigit() else 0))
        return ContainerReservation(
            envs={
                # the TPU runtime's visibility knob (the
                # NVIDIA_VISIBLE_DEVICES analog)
                "TPU_VISIBLE_CHIPS": chips,
                "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,1,{len(device_ids)}",
            }
        )

    def stats(self) -> DeviceStats:
        groups = self.fingerprint()
        return DeviceStats(
            instance_stats={
                d.id: {"healthy": 1.0}
                for g in groups
                for d in g.devices
            },
            timestamp_ns=time.time_ns(),
        )


def plugin() -> TPUDevicePlugin:
    return TPUDevicePlugin()
