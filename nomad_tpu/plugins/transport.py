"""Plugin transport: msgpack framing over unix-domain sockets.

Fills the role of go-plugin's gRPC-over-unix-socket channel (reference
``vendor/github.com/hashicorp/go-plugin``): the parent spawns the plugin
subprocess, reads a one-line handshake from its stdout naming the socket,
then issues method calls with the same length-framed msgpack envelope the
server RPC uses (rpc/codec, rpc/transport framing). Calls can block
server-side (``wait_task``), so the client keeps a small pool of
connections instead of serializing on one.
"""
from __future__ import annotations

import logging
import os
import socket
import socketserver
import subprocess
import sys
import threading
import time
from typing import Any, List, Optional

from ..rpc.codec import decode, encode
from ..rpc.transport import _recv_frame, _send_frame
from .base import HANDSHAKE_PREFIX

logger = logging.getLogger("nomad_tpu.plugins.transport")


class PluginError(Exception):
    pass


class PluginServer:
    """Runs inside the plugin subprocess: serves a plugin object's public
    methods over a unix socket."""

    def __init__(self, obj: object, socket_path: str) -> None:
        self.obj = obj
        self.socket_path = socket_path
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                try:
                    while True:
                        req = decode(_recv_frame(sock))
                        _send_frame(sock, encode(outer._dispatch(req)))
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._srv = Server(socket_path, Handler)
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, req: dict) -> dict:
        seq = req.get("seq", 0)
        method = req.get("method", "")
        fn = getattr(self.obj, method, None)
        if fn is None or method.startswith("_") or not callable(fn):
            return {"seq": seq, "error": f"unknown plugin method {method!r}", "body": None}
        try:
            return {"seq": seq, "error": None, "body": fn(*req.get("body", ()))}
        except Exception as e:  # noqa: BLE001 — errors cross the boundary as strings
            return {"seq": seq, "error": f"{type(e).__name__}: {e}", "body": None}

    def serve_forever(self) -> None:
        """Handshake on stdout, then serve until the parent disappears."""
        print(f"{HANDSHAKE_PREFIX}{self.socket_path}", flush=True)
        self._srv.serve_forever()

    def start_background(self) -> None:
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass


class PluginClient:
    """Parent-side connection to one plugin process (or socket)."""

    def __init__(self, socket_path: str, process: Optional[subprocess.Popen] = None,
                 max_conns: int = 8) -> None:
        self.socket_path = socket_path
        self.process = process
        self.max_conns = max_conns
        self._lock = threading.Lock()
        self._free: List[socket.socket] = []
        self._live = 0
        self._seq = 0
        self._closed = False

    # -- connection pool -------------------------------------------------

    def _acquire(self) -> socket.socket:
        with self._lock:
            if self._closed:
                raise PluginError("plugin client closed")
            if self._free:
                return self._free.pop()
            self._live += 1
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(self.socket_path)
            return s
        except OSError as e:
            with self._lock:
                self._live -= 1
            raise PluginError(f"plugin unreachable at {self.socket_path}: {e}") from e

    def _release(self, sock: socket.socket, broken: bool) -> None:
        with self._lock:
            if broken or self._closed or len(self._free) >= self.max_conns:
                self._live -= 1
                try:
                    sock.close()
                except OSError:
                    pass
            else:
                self._free.append(sock)

    def call(self, method: str, *args: Any, timeout: Optional[float] = None) -> Any:
        with self._lock:
            self._seq += 1
            seq = self._seq
        sock = self._acquire()
        broken = False
        try:
            sock.settimeout(timeout)
            _send_frame(sock, encode({"seq": seq, "method": method, "body": tuple(args)}))
            resp = decode(_recv_frame(sock))
        except (ConnectionError, OSError, socket.timeout) as e:
            broken = True
            raise PluginError(f"plugin call {method} failed: {e}") from e
        finally:
            self._release(sock, broken)
        if resp.get("error"):
            raise PluginError(resp["error"])
        return resp.get("body")

    def alive(self) -> bool:
        return self.process is None or self.process.poll() is None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            socks, self._free = self._free, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        if self.process is not None and self.process.poll() is None:
            if self.process.stdin is not None:
                try:
                    self.process.stdin.close()  # EOF: graceful exit signal
                except OSError:
                    pass
            self.process.terminate()
            try:
                self.process.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.process.kill()
                self.process.wait(timeout=3)


def spawn_plugin(argv: List[str], handshake_timeout: float = 10.0,
                 env: Optional[dict] = None) -> PluginClient:
    """Launch a plugin subprocess and wait for its stdout handshake
    (go-plugin client.Start)."""
    proc = subprocess.Popen(
        argv,
        stdin=subprocess.PIPE,  # held open; EOF tells the plugin to exit
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=env,
        text=True,
    )
    # A pump thread owns stdout: select() on a buffered text stream can
    # miss lines already pulled into the reader's buffer, and after the
    # handshake the pump keeps draining so a chatty plugin never blocks on
    # a full pipe.
    import queue as _queue

    lines: "_queue.Queue[str]" = _queue.Queue()

    def _pump() -> None:
        try:
            for out_line in proc.stdout:
                lines.put(out_line)
        except (ValueError, OSError):
            pass

    threading.Thread(target=_pump, daemon=True).start()

    deadline = time.monotonic() + handshake_timeout
    while True:
        if time.monotonic() > deadline:
            proc.kill()
            raise PluginError(f"plugin handshake timed out: {argv}")
        try:
            line = lines.get(timeout=0.1).strip()
        except _queue.Empty:
            if proc.poll() is not None:
                raise PluginError(
                    f"plugin exited ({proc.returncode}) before handshake: {argv}"
                )
            continue
        if line.startswith(HANDSHAKE_PREFIX):
            break
    socket_path = line[len(HANDSHAKE_PREFIX):]
    return PluginClient(socket_path, process=proc)


def serve_main(obj: object, socket_path: Optional[str] = None) -> None:
    """Plugin-side entrypoint: serve ``obj`` and exit when orphaned."""
    import tempfile

    if socket_path is None:
        socket_path = os.path.join(
            tempfile.mkdtemp(prefix="nomad-plugin-"), "plugin.sock"
        )
    server = PluginServer(obj, socket_path)

    # exit when the parent dies (go-plugin kills via stdin close)
    def watch_parent():
        try:
            sys.stdin.read()
        except Exception:  # noqa: BLE001
            pass
        os._exit(0)

    threading.Thread(target=watch_parent, daemon=True).start()
    server.serve_forever()
