"""RPC layer (reference nomad/rpc.go + helper/pool): msgpack over TCP with
typed-struct codec, leader forwarding, and the endpoint registry."""
from .codec import decode, encode, register_struct
from .endpoints import RemoteServerProxy, bind_server
from .transport import RPCClient, RPCError, RPCServer

__all__ = [
    "RPCClient",
    "RPCError",
    "RPCServer",
    "RemoteServerProxy",
    "bind_server",
    "decode",
    "encode",
    "register_struct",
]
