"""Wire codec: msgpack with a typed-dataclass extension.

Fills the role of the reference's msgpack codec over net/rpc
(nomad/rpc.go, helper/codec): structs cross the wire as msgpack maps
tagged with their registered type name and are rebuilt through a class
registry — never arbitrary deserialization (no pickle on the wire), so a
malicious peer can only produce known struct types.

Request envelopes are ``{"seq", "method", "body"}`` plus optional
routing flags (``no_forward``, ``region``) and the distributed-tracing
context under :data:`TRACE_KEY` — a ``{"trace_id", "span_id"}`` dict
(trace/context.py) that the server side re-activates so its handler
span becomes a child of the caller's span. Unknown envelope fields are
ignored by older peers, so the trace field is wire-compatible both ways.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Type

import msgpack

_TYPE_KEY = "__t"

#: request-envelope field carrying the TraceContext wire dict
TRACE_KEY = "trace"
_REGISTRY: Dict[str, Type] = {}
_REGISTRY_READY = False
_REGISTRY_LOCK = threading.Lock()


def _ensure_registry() -> None:
    """Thread-safe one-time full registration. Gating on registry
    non-emptiness is wrong twice over: a concurrent first call can observe
    a PARTIALLY-filled registry mid-registration, and an early
    register_struct() call would suppress full registration forever."""
    global _REGISTRY_READY
    if _REGISTRY_READY:
        return
    with _REGISTRY_LOCK:
        if _REGISTRY_READY:
            return
        _register_all_structs()
        _REGISTRY_READY = True


def register_struct(cls: Type) -> Type:
    """Allow a dataclass across the wire."""
    _REGISTRY[cls.__name__] = cls
    return cls


def _register_all_structs() -> None:
    from ..structs import structs as s

    for name in dir(s):
        obj = getattr(s, name)
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            _REGISTRY[obj.__name__] = obj
    # non-struct payloads that ride raft/rpc
    from ..client.drivers.base import (
        Capabilities,
        ExitResult,
        Fingerprint,
        TaskConfig,
        TaskHandle,
        TaskStats,
        TaskStatus,
    )

    for cls in (Capabilities, ExitResult, Fingerprint, TaskConfig, TaskHandle,
                TaskStats, TaskStatus):
        _REGISTRY[cls.__name__] = cls

    from ..plugins.base import PluginInfo
    from ..plugins.device import (
        ContainerReservation,
        DetectedDevice,
        DeviceGroup,
        DeviceSpec,
        DeviceStats,
        Mount,
    )

    for cls in (PluginInfo, ContainerReservation, DetectedDevice, DeviceGroup,
                DeviceSpec, DeviceStats, Mount):
        _REGISTRY[cls.__name__] = cls

    from ..client.allocdir import TaskDir

    _REGISTRY[TaskDir.__name__] = TaskDir

    # ACL + operator payloads (ride raft snapshots and RPC)
    from ..structs import acl as acl_structs

    for name in dir(acl_structs):
        obj = getattr(acl_structs, name)
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            _REGISTRY[obj.__name__] = obj

    from ..server.autopilot import AutopilotConfig

    _REGISTRY[AutopilotConfig.__name__] = AutopilotConfig


def _to_wire(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {_TYPE_KEY: type(obj).__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = _to_wire(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        # tuple keys (e.g. (namespace, job_id)) become tagged lists
        enc = {}
        tuple_keys = False
        for k, v in obj.items():
            if isinstance(k, tuple):
                tuple_keys = True
                break
        if tuple_keys:
            return {
                _TYPE_KEY: "__tdict",
                "items": [[_to_wire(list(k) if isinstance(k, tuple) else k), _to_wire(v)]
                          for k, v in obj.items()],
            }
        for k, v in obj.items():
            enc[k] = _to_wire(v)
        return enc
    if isinstance(obj, tuple):
        return {_TYPE_KEY: "__tuple", "items": [_to_wire(v) for v in obj]}
    if isinstance(obj, set):
        return {_TYPE_KEY: "__set", "items": [_to_wire(v) for v in obj]}
    if isinstance(obj, list):
        return [_to_wire(v) for v in obj]
    return obj


def _from_wire(obj: Any) -> Any:
    if isinstance(obj, dict):
        tname = obj.get(_TYPE_KEY)
        if tname == "__tuple":
            return tuple(_from_wire(v) for v in obj["items"])
        if tname == "__set":
            return set(_from_wire(v) for v in obj["items"])
        if tname == "__tdict":
            return {
                tuple(_from_wire(k)) if isinstance(k, list) else _from_wire(k): _from_wire(v)
                for k, v in obj["items"]
            }
        if tname is not None:
            cls = _REGISTRY.get(tname)
            if cls is None:
                raise ValueError(f"unknown wire type {tname!r}")
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {
                k: _from_wire(v)
                for k, v in obj.items()
                if k != _TYPE_KEY and k in field_names
            }
            return cls(**kwargs)
        return {k: _from_wire(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_wire(v) for v in obj]
    return obj


def encode(obj: Any) -> bytes:
    _ensure_registry()
    return msgpack.packb(_to_wire(obj), use_bin_type=True)


def decode(data: bytes) -> Any:
    _ensure_registry()
    return _from_wire(msgpack.unpackb(data, raw=False, strict_map_key=False))
