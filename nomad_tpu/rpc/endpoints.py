"""RPC endpoint surface: binds a Server to the transport.

Fills the role of the reference's ``nomad/*_endpoint.go`` files — one
registry entry per noun (server.go:236 ``endpoints`` struct), method names
matching the reference RPC names ("Node.Register", "Job.Register",
"Eval.Dequeue"...). ``RemoteServerProxy`` is the client-side counterpart
the agent dials (client/rpc.go), satisfying the same interface as the
in-process ``ServerProxy``.
"""
from __future__ import annotations

from typing import List, Optional

from ..structs.structs import Allocation, Job, Node
from ..trace import context as xtrace
from ..watch.blocking import blocking_read
from ..watch.stale import read_meta
from . import transport
from .transport import RPCClient, RPCServer


def bind_server(server, rpc: RPCServer) -> None:
    """Register every server endpoint on the transport."""

    def state():
        # resolved per-call, never captured: fsm.restore() (snapshot
        # install on a rejoining replica) REPLACES server.fsm.state, and
        # endpoints bound to the old store would answer from pre-restore
        # state forever (empty, on a crash-restarted follower)
        return server.fsm.state

    def serve_read(table, run, query_opts, key=None):
        """The one funnel every read endpoint routes through
        (lint: blocking-read-discipline). Without ``query_opts`` the
        response is the legacy bare result — old callers are untouched.
        With a QueryOptions the read gets reference blocking semantics
        (min_query_index park on the watch hub, max_query_time deadline)
        and returns ``[result, QueryMeta]`` with the index stamped under
        the same lock hold as the query."""
        if query_opts is None:
            return run(state())
        return blocking_read(
            state, server.watch_hub, run, table, query_opts, key=key,
            meta=read_meta(server, rpc),
        )

    # -- Status --------------------------------------------------------
    rpc.register("Status.ping", lambda: "pong")
    rpc.register("Status.leader", lambda: list(rpc.leader_addr or rpc.addr))

    # -- Node ----------------------------------------------------------
    rpc.register("Node.Register", server.register_node)
    rpc.register("Node.Deregister", server.deregister_node)
    rpc.register("Node.Heartbeat", server.heartbeat)
    rpc.register("Node.UpdateStatus", server.update_node_status)
    rpc.register("Node.UpdateDrain", server.update_node_drain)
    rpc.register("Node.UpdateEligibility", server.update_node_eligibility)
    rpc.register("Node.UpdateAlloc", server.update_allocs_from_client)
    rpc.register(
        "Node.List",
        lambda query_opts=None: serve_read(
            "nodes",
            lambda s: [n.without_secret() for n in s.nodes()],
            query_opts,
        ),
    )
    rpc.register(
        "Node.GetNode",
        lambda node_id, query_opts=None: serve_read(
            "nodes",
            lambda s: (lambda n: n.without_secret() if n else None)(
                s.node_by_id(node_id)
            ),
            query_opts, key=node_id,
        ),
    )

    def get_client_allocs(node_id: str, min_index: int, timeout: float):
        def run(s):
            out = []
            for a in s.allocs_by_node(node_id):
                if a.job is None:
                    a = a.copy_skip_job()
                    a.job = s.job_by_id(a.namespace, a.job_id)
                out.append(a)
            return out

        allocs, index = state().blocking_query(run, min_index, timeout=timeout)
        return [allocs, index]

    # blocking-read-waiver: pre-watch long-poll protocol — carries its own
    # min_index/timeout args through StateStore.blocking_query, and the
    # client agents' pull loop depends on the bare [allocs, index] shape
    rpc.register("Node.GetClientAllocs", get_client_allocs)
    rpc.register("Node.DeriveVaultToken", server.derive_vault_token)

    # -- Job -----------------------------------------------------------
    rpc.register("Job.Register", server.register_job)
    rpc.register("Job.Deregister", server.deregister_job)
    rpc.register(
        "Job.GetJob",
        lambda ns, job_id, query_opts=None: serve_read(
            "jobs", lambda s: s.job_by_id(ns, job_id),
            query_opts, key=(ns, job_id),
        ),
    )
    rpc.register(
        "Job.List",
        lambda query_opts=None: serve_read(
            "jobs", lambda s: s.jobs(), query_opts,
        ),
    )
    rpc.register(
        "Job.Allocations",
        lambda ns, job_id, query_opts=None: serve_read(
            "allocs", lambda s: s.allocs_by_job(ns, job_id, True), query_opts,
        ),
    )
    rpc.register(
        "Job.Evaluations",
        lambda ns, job_id, query_opts=None: serve_read(
            "evals", lambda s: s.evals_by_job(ns, job_id), query_opts,
        ),
    )
    rpc.register(
        "Job.GetJobVersions",
        lambda ns, job_id, query_opts=None: serve_read(
            "jobs", lambda s: s.job_versions.get((ns, job_id), []),
            query_opts, key=(ns, job_id),
        ),
    )
    rpc.register(
        "Job.Summary",
        lambda ns, job_id, query_opts=None: serve_read(
            # summaries are alloc-status rollups: the allocs table is
            # what moves them, so that's the watched table
            "allocs", lambda s: s.job_summary(ns, job_id), query_opts,
        ),
    )
    # write endpoints the HTTP agent reaches through leader_forward when
    # serving on a follower (reference job_endpoint.go Evaluate/Dispatch/
    # Revert/Stable, alloc_endpoint.go Stop, node_endpoint.go Evaluate,
    # core GC trigger)
    rpc.register("Job.Evaluate", server.evaluate_job)
    rpc.register("Job.Dispatch", server.dispatch_job)
    rpc.register("Job.Revert", server.revert_job)
    rpc.register("Job.Stability", server.set_job_stability)
    rpc.register("Alloc.Stop", server.stop_alloc)
    rpc.register("Node.Evaluate", server.create_node_evals)
    rpc.register("System.GC", server.force_gc)

    # -- Eval ----------------------------------------------------------
    rpc.register(
        "Eval.GetEval",
        lambda eval_id, query_opts=None: serve_read(
            "evals", lambda s: s.eval_by_id(eval_id), query_opts, key=eval_id,
        ),
    )
    rpc.register(
        "Eval.List",
        lambda query_opts=None: serve_read(
            "evals", lambda s: s.evals(), query_opts,
        ),
    )
    rpc.register(
        "Eval.Allocations",
        lambda eval_id, query_opts=None: serve_read(
            "allocs", lambda s: s.allocs_by_eval(eval_id), query_opts,
        ),
    )

    # -- worker protocol (follower workers dequeue from the leader's
    #    broker and submit plans to its queue: worker.go:161 Eval.Dequeue,
    #    :277 Plan.Submit — the reference's horizontal scheduler scaling)
    def eval_dequeue(schedulers, timeout: float):
        ev, token = server.eval_broker.dequeue(schedulers, timeout=min(timeout, 2.0))
        return [ev, token or ""]

    rpc.register("Eval.Dequeue", eval_dequeue)
    rpc.register("Eval.Ack", server.eval_broker.ack)
    rpc.register("Eval.Nack", server.eval_broker.nack)

    def eval_update(evals):
        return server.raft_apply("eval-update", evals)[0]

    rpc.register("Eval.Update", eval_update)

    def eval_reblock(evaluation, token: str):
        if server.eval_broker.outstanding(evaluation.id) != token:
            raise ValueError(f"eval {evaluation.id} token mismatch")
        server.raft_apply("eval-update", [evaluation])
        server.blocked_evals.reblock(evaluation, token)

    rpc.register("Eval.Reblock", eval_reblock)

    def plan_submit(plan):
        # pause the nack timer while the plan waits in the queue, exactly
        # as the colocated worker does (worker.go:277)
        server.eval_broker.pause_nack_timeout(plan.eval_id, plan.eval_token)
        try:
            pending = server.plan_queue.enqueue(plan)
            return pending.future.result(timeout=60)
        finally:
            try:
                server.eval_broker.resume_nack_timeout(plan.eval_id, plan.eval_token)
            except Exception:  # noqa: BLE001 — eval may have been acked
                pass

    rpc.register("Plan.Submit", plan_submit)

    # -- Alloc ---------------------------------------------------------
    rpc.register(
        "Alloc.GetAlloc",
        lambda alloc_id, query_opts=None: serve_read(
            "allocs", lambda s: s.alloc_by_id(alloc_id),
            query_opts, key=alloc_id,
        ),
    )
    rpc.register(
        "Alloc.List",
        lambda query_opts=None: serve_read(
            "allocs", lambda s: s.allocs(), query_opts,
        ),
    )

    # -- Deployment ----------------------------------------------------
    dw = server.deployment_watcher
    rpc.register(
        "Deployment.List",
        lambda query_opts=None: serve_read(
            "deployments", lambda s: s.deployments(), query_opts,
        ),
    )
    rpc.register(
        "Deployment.GetDeployment",
        lambda deployment_id, query_opts=None: serve_read(
            "deployments", lambda s: s.deployment_by_id(deployment_id),
            query_opts, key=deployment_id,
        ),
    )
    rpc.register("Deployment.Promote", dw.promote)
    rpc.register("Deployment.Pause", dw.pause)
    rpc.register("Deployment.Fail", dw.fail)
    rpc.register("Deployment.SetAllocHealth", dw.set_alloc_health)

    # -- Periodic ------------------------------------------------------
    rpc.register("Periodic.Force", server.periodic_dispatcher.force_launch)

    # -- ACL federation (leader.go:997/:1138 replication source) -------
    # blocking-read-waiver: cross-region replication pull with its own
    # cursor protocol; replicators poll, they never park
    rpc.register("ACL.ListReplication", server.list_acl_for_replication)

    # -- Operator ------------------------------------------------------
    def scheduler_get_config():
        index, config = state().scheduler_config()
        return [index, config]

    rpc.register("Operator.SchedulerGetConfiguration", scheduler_get_config)
    rpc.register(
        "Operator.SchedulerSetConfiguration",
        lambda config: server.raft_apply("scheduler-config", config)[0],
    )
    # raft introspection + snapshot trigger (operator_endpoint.go
    # RaftGetConfiguration / the `nomad operator snapshot save` surface).
    # Callers probing a SPECIFIC replica (the chaos crash harness polling
    # each survivor for leadership/catch-up) must pass no_forward=True,
    # or leader forwarding answers for the wrong node.
    rpc.register("Operator.RaftStats",
                 lambda: server.raft.stats(server.peer))
    rpc.register("Operator.SnapshotSave",
                 lambda: server.raft.snapshot(server.peer))
    rpc.register("Eval.BrokerStats", server.eval_broker.stats)

    # -- Trace (nomad-xtrace collector surface) ------------------------
    # Drains THIS replica's span ring + per-method RPC table. Collectors
    # keep a per-replica ``after_seq`` cursor (the returned ``next_seq``)
    # so repeated drains are incremental and idempotent — and like
    # RaftStats they must pass no_forward=True, or leader forwarding
    # exports the wrong node's ring.
    def trace_export(after_seq: int = 0):
        out = xtrace.export(after_seq=after_seq)
        out["rpc"] = transport.rpc_stats(wire=True)
        return out

    rpc.register("Trace.Export", trace_export)

    # -- Watch (nomad-watch hub introspection) -------------------------
    # THIS replica's parked-watcher depth + wakeup/coalesce counters;
    # like RaftStats, probers of a specific replica pass no_forward=True
    rpc.register("Watch.Stats", server.watch_hub.stats)


class RemoteServerProxy:
    """Client-side server connection over the wire (client/rpc.go) —
    drop-in for the in-process ``client.ServerProxy``."""

    def __init__(self, host: str, port: int, tls=None) -> None:
        self.rpc = RPCClient(host, port, tls=tls)
        # a second connection so long-poll pulls don't block status syncs
        self.rpc_blocking = RPCClient(host, port, timeout=90.0, tls=tls)

    def register_node(self, node: Node) -> float:
        return self.rpc.call("Node.Register", node)

    def heartbeat(self, node_id: str) -> float:
        return self.rpc.call("Node.Heartbeat", node_id)

    def pull_allocs(self, node_id: str, min_index: int, timeout: float):
        allocs, index = self.rpc_blocking.call(
            "Node.GetClientAllocs", node_id, min_index, timeout
        )
        return allocs, index

    def update_allocs(self, allocs: List[Allocation]) -> None:
        self.rpc.call("Node.UpdateAlloc", allocs)

    def derive_vault_token(
        self, alloc_id: str, task_name: str, node_id: str = "", node_secret: str = ""
    ) -> str:
        tokens = self.rpc.call(
            "Node.DeriveVaultToken", alloc_id, [task_name], node_id, node_secret
        )
        return tokens[task_name]

    def alloc_info(self, alloc_id: str):
        alloc = self.rpc.call("Alloc.GetAlloc", alloc_id)
        if alloc is None:
            return None
        node = self.rpc.call("Node.GetNode", alloc.node_id)
        return {
            "client_status": alloc.client_status,
            "node_http_addr": node.http_addr if node is not None else "",
        }

    def close(self) -> None:
        self.rpc.close()
        self.rpc_blocking.close()
