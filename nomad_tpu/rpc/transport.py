"""TCP RPC transport: length-framed msgpack request/response.

Fills the role of reference ``nomad/rpc.go`` + ``helper/pool/``: msgpack
net/rpc over TCP with connection reuse and leader forwarding
(rpc.go:409 ``forward`` / :493 forwardLeader). Framing is
[u32 length][msgpack envelope]; the envelope is
{"seq", "method", "body"} out and {"seq", "error", "body"} back. One
server thread per connection (yamux multiplexing is unnecessary when each
connection already pipelines request/response pairs).
"""
from __future__ import annotations

import logging
import random
import socket
import socketserver
import ssl
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from .codec import decode, encode

_LEN = struct.Struct(">I")
MAX_FRAME = 256 << 20


class RPCError(Exception):
    pass


class TLSConfig:
    """Mutual-TLS material (reference helper/tlsutil + agent tls stanza):
    one CA, one cert+key per agent, client certs required on both sides.

    When ``server_name`` is set (e.g. ``server.<region>.nomad``) and
    ``verify_server_hostname`` is true, RPC clients verify the server's
    certificate SAN against that pinned name — so a mere cluster-CA cert
    holder (a client agent's cert) cannot impersonate a server
    (the reference's ``verify_server_hostname`` role pinning). Pass
    ``verify_server_hostname=False`` to opt out (the
    ``api.Config.tls_skip_verify`` posture)."""

    def __init__(self, ca_file: str, cert_file: str, key_file: str,
                 verify: bool = True, server_name: str = "",
                 verify_server_hostname: bool = True) -> None:
        self.ca_file = ca_file
        self.cert_file = cert_file
        self.key_file = key_file
        self.verify = verify
        self.server_name = server_name
        self.verify_server_hostname = verify_server_hostname
        self._server_ctx: Optional[ssl.SSLContext] = None
        self._client_ctx: Optional[ssl.SSLContext] = None
        self._http_client_ctx: Optional[ssl.SSLContext] = None
        self._ctx_lock = threading.Lock()

    @property
    def pin_server_name(self) -> bool:
        return bool(self.server_name) and self.verify_server_hostname and self.verify

    def server_context(self) -> ssl.SSLContext:
        # built once and shared: SSLContext is designed for reuse, and the
        # per-connection path must not re-read key material from disk
        with self._ctx_lock:
            if self._server_ctx is None:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self.cert_file, self.key_file)
                ctx.load_verify_locations(self.ca_file)
                if self.verify:
                    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
                self._server_ctx = ctx
            return self._server_ctx

    def _build_client_ctx(self, check_hostname: bool) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.check_hostname = check_hostname
        ctx.verify_mode = ssl.CERT_REQUIRED if self.verify else ssl.CERT_NONE
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Context for the RPC plane: pins the server SAN when
        ``server_name`` is configured (dial with
        ``server_hostname=self.server_name``, not the peer address)."""
        with self._ctx_lock:
            if self._client_ctx is None:
                self._client_ctx = self._build_client_ctx(self.pin_server_name)
            return self._client_ctx

    def http_client_context(self) -> ssl.SSLContext:
        """Context for intra-cluster HTTPS (log fetch, ephemeral-disk
        migration): peers are client agents at dynamic addresses whose
        certs carry role names, not IPs — certificate chain is still
        verified against the cluster CA, hostname is not."""
        with self._ctx_lock:
            if self._http_client_ctx is None:
                self._http_client_ctx = self._build_client_ctx(False)
            return self._http_client_ctx


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_read_exact(sock, 4))
    if length > MAX_FRAME:
        raise RPCError(f"frame too large: {length}")
    return _read_exact(sock, length)


class RPCServer:
    """Dispatches "Noun.Verb" methods to registered handlers."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        region: str = "global",
        tls: Optional["TLSConfig"] = None,
    ) -> None:
        self.logger = logging.getLogger("nomad_tpu.rpc.server")
        self.tls = tls
        self.handlers: Dict[str, Callable[..., Any]] = {}
        # set to (host, port) of the leader for transparent forwarding
        self.leader_addr: Optional[Tuple[str, int]] = None
        self.is_leader: Callable[[], bool] = lambda: True
        self._forward_pool: Optional["RPCClient"] = None
        # cross-region federation (rpc.go:502 forwardRegion): resolves a
        # region name to that region's server RPC addrs, fed by gossip
        self.region = region
        self.region_servers: Optional[Callable[[str], list]] = None
        self._region_pools: Dict[Tuple[str, int], "RPCClient"] = {}
        self._region_pools_lock = threading.Lock()

        outer = self
        self._active_conns: set = set()
        self._active_lock = threading.Lock()
        self._stopping = False

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if outer.tls is not None:
                    try:
                        sock = outer.tls.server_context().wrap_socket(
                            sock, server_side=True
                        )
                    except (OSError, ssl.SSLError) as e:
                        outer.logger.debug("TLS handshake failed: %s", e)
                        return
                with outer._active_lock:
                    if outer._stopping:
                        # raced a stop(): close instead of serving — a
                        # dead server must not keep answering
                        try:
                            sock.close()
                        except OSError:
                            pass
                        return
                    outer._active_conns.add(sock)
                try:
                    while True:
                        frame = _recv_frame(sock)
                        req = decode(frame)
                        resp = outer._dispatch(req)
                        _send_frame(sock, encode(resp))
                except (ConnectionError, OSError, ssl.SSLError):
                    pass
                finally:
                    with outer._active_lock:
                        outer._active_conns.discard(sock)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

            def handle_error(self, request, client_address):
                # peer-side tear-downs stay quiet; anything else reaching
                # here escaped the handler's own guards and is a genuine
                # server bug — keep its full traceback, just via logging
                import ssl as ssl_mod
                import sys

                exc = sys.exc_info()[1]
                if isinstance(exc, (ConnectionError, ssl_mod.SSLError,
                                    TimeoutError, BrokenPipeError)):
                    outer.logger.debug(
                        "connection from %s dropped: %s", client_address, exc
                    )
                else:
                    outer.logger.warning(
                        "request from %s crashed", client_address,
                        exc_info=True,
                    )

        self._tcp = Server((host, port), Handler)
        self.addr: Tuple[str, int] = self._tcp.server_address
        self._thread: Optional[threading.Thread] = None

    def register(self, method: str, fn: Callable[..., Any]) -> None:
        self.handlers[method] = fn

    def register_endpoint(self, noun: str, obj: object) -> None:
        """Every public method of ``obj`` becomes "<noun>.<method>"
        (the reference's endpoint struct registry, server.go:236)."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self.register(f"{noun}.{name}", fn)

    FORWARDED = "forwarded"
    LOCAL_ONLY = {"Status.ping", "Status.leader"}

    def _dispatch(self, req: dict) -> dict:
        seq = req.get("seq", 0)
        method = req.get("method", "")
        body = req.get("body", ())
        fn = self.handlers.get(method)
        if fn is None:
            return {"seq": seq, "error": f"unknown method {method!r}", "body": None}
        try:
            # region forwarding (rpc.go:502 forwardRegion): a request naming
            # another region hops to any server there, which then applies
            # its own leader forwarding
            req_region = req.get("region")
            if req_region and req_region != self.region:
                result = self._forward_region(req_region, method, body)
            # leader forwarding (rpc.go:409): followers proxy writes
            elif (
                not self.is_leader()
                and self.leader_addr is not None
                and self.leader_addr != self.addr
                and method not in self.LOCAL_ONLY
                and not req.get("no_forward")
            ):
                result = self._forward(method, body)
            else:
                result = fn(*body)
            return {"seq": seq, "error": None, "body": result}
        except Exception as e:  # noqa: BLE001
            return {"seq": seq, "error": f"{type(e).__name__}: {e}", "body": None}

    def _forward(self, method: str, body) -> Any:
        if self._forward_pool is None or self._forward_pool.addr != self.leader_addr:
            if self._forward_pool is not None:
                self._forward_pool.close()
            self._forward_pool = RPCClient(*self.leader_addr, tls=self.tls)
        return self._forward_pool.call(method, *body, no_forward=True)

    def _forward_region(self, region: str, method: str, body) -> Any:
        servers = self.region_servers(region) if self.region_servers else []
        if not servers:
            raise RPCError(f"no path to region {region!r}")
        addr = tuple(random.choice(servers))
        with self._region_pools_lock:
            pool = self._region_pools.get(addr)
            if pool is None:
                pool = self._region_pools[addr] = RPCClient(*addr, tls=self.tls)
        # keep the region tag: the remote sees its own region and serves it
        return pool.call(method, *body, region=region)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="rpc-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        # a stopped server must stop ANSWERING, not just accepting: close
        # established connections too, or clients pinned to a dead server
        # never observe the death (and never fail over)
        with self._active_lock:
            self._stopping = True
            conns = list(self._active_conns)
            self._active_conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._forward_pool is not None:
            self._forward_pool.close()
        with self._region_pools_lock:
            pools = list(self._region_pools.values())
            self._region_pools.clear()
        for pool in pools:
            pool.close()


class RPCClient:
    """Pooled client: one persistent connection, serialized calls
    (helper/pool ConnPool's role for a single peer)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 tls: Optional[TLSConfig] = None) -> None:
        self.addr = (host, port)
        self.timeout = timeout
        self.tls = tls
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seq = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.tls is not None:
                sni = (
                    self.tls.server_name
                    if self.tls.pin_server_name
                    else self.addr[0]
                )
                s = self.tls.client_context().wrap_socket(
                    s, server_hostname=sni
                )
            self._sock = s
        return self._sock

    def call(
        self,
        method: str,
        *args: Any,
        no_forward: bool = False,
        region: Optional[str] = None,
        timeout: Optional[float] = None,
        no_retry: bool = False,
    ) -> Any:
        """``timeout`` overrides the connection timeout for this call;
        ``no_retry`` disables the reconnect-resend (required for
        non-idempotent calls like Plan.Submit, where a resend would
        enqueue the work twice)."""
        with self._lock:
            self._seq += 1
            req = {"seq": self._seq, "method": method, "body": tuple(args)}
            if no_forward:
                req["no_forward"] = True
            if region:
                req["region"] = region
            try:
                sock = self._connect()
                if timeout is not None:
                    sock.settimeout(timeout)
                try:
                    _send_frame(sock, encode(req))
                    resp = decode(_recv_frame(sock))
                finally:
                    if timeout is not None:
                        sock.settimeout(self.timeout)
            except (ConnectionError, OSError):
                self._close_locked()
                if no_retry:
                    raise
                # one reconnect attempt (pool behavior on dead conns)
                sock = self._connect()
                if timeout is not None:
                    sock.settimeout(timeout)
                try:
                    _send_frame(sock, encode(req))
                    resp = decode(_recv_frame(sock))
                finally:
                    if timeout is not None:
                        try:
                            sock.settimeout(self.timeout)
                        except OSError:
                            pass
        if resp.get("error"):
            raise RPCError(resp["error"])
        return resp.get("body")

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()


class LeaderConn:
    """Thread-safe cache of one RPCClient keyed on the (changing) leader
    address: get() reconnects when the address moves, close() tears down.
    Shared by everything that follows the leader (follower workers, the
    colocated-client failover proxy, RPC write forwarding)."""

    def __init__(self, timeout: float = 30.0,
                 tls: Optional[TLSConfig] = None) -> None:
        self.timeout = timeout
        self.tls = tls
        self._lock = threading.Lock()
        self._client: Optional[RPCClient] = None

    def get(self, addr) -> RPCClient:
        addr = tuple(addr)
        with self._lock:
            if self._client is not None and self._client.addr != addr:
                self._client.close()
                self._client = None
            if self._client is None:
                self._client = RPCClient(*addr, timeout=self.timeout, tls=self.tls)
            return self._client

    def close(self) -> None:
        with self._lock:
            if self._client is not None:
                self._client.close()
                self._client = None
