"""TCP RPC transport: length-framed msgpack request/response.

Fills the role of reference ``nomad/rpc.go`` + ``helper/pool/``: msgpack
net/rpc over TCP with connection reuse and leader forwarding
(rpc.go:409 ``forward`` / :493 forwardLeader). Framing is
[u32 length][msgpack envelope]; the envelope is
{"seq", "method", "body"[, "trace"]} out and {"seq", "error", "body"}
back — ``trace`` is the distributed-tracing context (codec.TRACE_KEY,
trace/context.py). One server thread per connection (yamux multiplexing
is unnecessary when each connection already pipelines request/response
pairs).

Telemetry (the reference exports yamux/raft RPC metrics via go-metrics;
here the transport itself is the choke point): every dispatched method
records latency into a log-bucketed histogram plus error /
``NotLeaderError`` counters and request/response frame bytes, under the
``nomad.rpc.<method>.*`` family and in a module-level per-method table
(:func:`rpc_stats`) that the ``Trace.Export`` RPC and the flight
recorder's ``rpc`` probe read. Client calls open a ``client`` span and
inject the ambient TraceContext; the server opens a child ``server``
span around dispatch, so a forwarded write shows up as
client → server(follower) → client(forward) → server(leader) in the
stitched trace.
"""
from __future__ import annotations

import logging
import random
import socket
import socketserver
import ssl
import struct
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from ..trace import context as xtrace
from ..utils import metric_names, metrics
from ..utils.lock_witness import module_witness_lock
from ..utils.race_witness import tracked_dict
from ..utils.metrics import LogHistogram
from .codec import TRACE_KEY, decode, encode

_LEN = struct.Struct(">I")
MAX_FRAME = 256 << 20


class RPCError(Exception):
    pass


class FrameError(ConnectionError):
    """A frame-level failure (short read, dropped send) tagged with the
    method, peer address and bytes transferred — a ``ConnectionError``
    subclass so every retry/failover path that handles peer death keeps
    working, but a chaos-run log line now says WHICH call to WHOM died
    mid-frame instead of a bare "peer closed"."""


# -- per-method server telemetry -------------------------------------------


class _MethodStats:
    __slots__ = ("calls", "errors", "not_leader", "req_bytes",
                 "resp_bytes", "hist")

    def __init__(self) -> None:
        self.calls = 0
        self.errors = 0
        self.not_leader = 0
        self.req_bytes = 0
        self.resp_bytes = 0
        self.hist = LogHistogram()

    def row(self, wire: bool = False) -> Dict[str, object]:
        out: Dict[str, object] = {
            "calls": self.calls,
            "errors": self.errors,
            "not_leader": self.not_leader,
            "req_bytes": self.req_bytes,
            "resp_bytes": self.resp_bytes,
            "latency_ms_p50": self.hist.percentile(0.50),
            "latency_ms_p95": self.hist.percentile(0.95),
            "latency_ms_p99": self.hist.percentile(0.99),
        }
        if wire:
            # mergeable across replicas: elementwise bucket addition
            out["latency_hist"] = self.hist.to_wire()
        return out


_rpc_lock = module_witness_lock("rpc.transport._rpc_lock")
_rpc_stats: Dict[str, _MethodStats] = tracked_dict("transport._rpc_stats", {})
_rpc_inflight = 0


def _record_dispatch(method: str, elapsed_s: float,
                     error: Optional[str]) -> None:
    ms = elapsed_s * 1000.0
    not_leader = bool(error) and error.startswith("NotLeaderError")
    with _rpc_lock:
        st = _rpc_stats.setdefault(method, _MethodStats())
        st.calls += 1
        st.hist.add(ms)
        if error:
            st.errors += 1
        if not_leader:
            st.not_leader += 1
    # the method set is bounded by the bind_server registry (unknown
    # methods never reach here), so these dynamic names stay bounded
    metric_names.family_sample("nomad.rpc", f"{method}.latency_ms", ms)
    if error:
        metric_names.family_counter("nomad.rpc", f"{method}.errors")
    if not_leader:
        metric_names.family_counter("nomad.rpc", f"{method}.not_leader")


def _record_frame_bytes(method: str, req_bytes: int, resp_bytes: int) -> None:
    with _rpc_lock:
        st = _rpc_stats.setdefault(method, _MethodStats())
        st.req_bytes += req_bytes
        st.resp_bytes += resp_bytes
    metric_names.family_sample("nomad.rpc", f"{method}.req_bytes", req_bytes)
    metric_names.family_sample("nomad.rpc", f"{method}.resp_bytes", resp_bytes)


def rpc_stats(wire: bool = False) -> Dict[str, Dict[str, object]]:
    """Per-method table for this process: counts, byte totals, latency
    percentiles (``wire=True`` adds the raw histogram buckets so a
    collector can merge tables across replicas)."""
    with _rpc_lock:
        items = list(_rpc_stats.items())
    return {m: st.row(wire) for m, st in sorted(items)}


def rpc_stats_brief() -> Dict[str, object]:
    """Cheap flight-recorder probe: totals only, no percentile walks."""
    with _rpc_lock:
        return {
            "methods": len(_rpc_stats),
            "inflight": _rpc_inflight,
            "calls": sum(st.calls for st in _rpc_stats.values()),
            "errors": sum(st.errors for st in _rpc_stats.values()),
            "not_leader": sum(st.not_leader for st in _rpc_stats.values()),
        }


def reset_rpc_stats() -> None:
    # re-mint through the factory so a race witness armed after import
    # still gets a tracked table (the import-time one predates arming)
    global _rpc_stats, _rpc_inflight
    with _rpc_lock:
        _rpc_stats = tracked_dict("transport._rpc_stats", {})
        _rpc_inflight = 0


def merge_rpc_tables(
    tables: Iterable[Dict[str, Dict[str, object]]],
) -> Dict[str, Dict[str, object]]:
    """Merge wire-form per-method tables (``rpc_stats(wire=True)``) from
    N replicas into one cluster table: counters add, histogram buckets
    add elementwise, and the percentiles are recomputed from the MERGED
    histogram — not averaged, so a single slow replica still moves the
    cluster p99."""
    merged: Dict[str, _MethodStats] = {}
    for table in tables:
        for method, row in (table or {}).items():
            st = merged.setdefault(method, _MethodStats())
            st.calls += int(row.get("calls", 0))
            st.errors += int(row.get("errors", 0))
            st.not_leader += int(row.get("not_leader", 0))
            st.req_bytes += int(row.get("req_bytes", 0))
            st.resp_bytes += int(row.get("resp_bytes", 0))
            counts = row.get("latency_hist")
            if counts:
                st.hist.merge(LogHistogram(counts))
    return {m: st.row() for m, st in sorted(merged.items())}


class TLSConfig:
    """Mutual-TLS material (reference helper/tlsutil + agent tls stanza):
    one CA, one cert+key per agent, client certs required on both sides.

    When ``server_name`` is set (e.g. ``server.<region>.nomad``) and
    ``verify_server_hostname`` is true, RPC clients verify the server's
    certificate SAN against that pinned name — so a mere cluster-CA cert
    holder (a client agent's cert) cannot impersonate a server
    (the reference's ``verify_server_hostname`` role pinning). Pass
    ``verify_server_hostname=False`` to opt out (the
    ``api.Config.tls_skip_verify`` posture)."""

    def __init__(self, ca_file: str, cert_file: str, key_file: str,
                 verify: bool = True, server_name: str = "",
                 verify_server_hostname: bool = True) -> None:
        self.ca_file = ca_file
        self.cert_file = cert_file
        self.key_file = key_file
        self.verify = verify
        self.server_name = server_name
        self.verify_server_hostname = verify_server_hostname
        self._server_ctx: Optional[ssl.SSLContext] = None
        self._client_ctx: Optional[ssl.SSLContext] = None
        self._http_client_ctx: Optional[ssl.SSLContext] = None
        self._ctx_lock = threading.Lock()

    @property
    def pin_server_name(self) -> bool:
        return bool(self.server_name) and self.verify_server_hostname and self.verify

    def server_context(self) -> ssl.SSLContext:
        # built once and shared: SSLContext is designed for reuse, and the
        # per-connection path must not re-read key material from disk
        with self._ctx_lock:
            if self._server_ctx is None:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self.cert_file, self.key_file)
                ctx.load_verify_locations(self.ca_file)
                if self.verify:
                    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
                self._server_ctx = ctx
            return self._server_ctx

    def _build_client_ctx(self, check_hostname: bool) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.check_hostname = check_hostname
        ctx.verify_mode = ssl.CERT_REQUIRED if self.verify else ssl.CERT_NONE
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Context for the RPC plane: pins the server SAN when
        ``server_name`` is configured (dial with
        ``server_hostname=self.server_name``, not the peer address)."""
        with self._ctx_lock:
            if self._client_ctx is None:
                self._client_ctx = self._build_client_ctx(self.pin_server_name)
            return self._client_ctx

    def http_client_context(self) -> ssl.SSLContext:
        """Context for intra-cluster HTTPS (log fetch, ephemeral-disk
        migration): peers are client agents at dynamic addresses whose
        certs carry role names, not IPs — certificate chain is still
        verified against the cluster CA, hostname is not."""
        with self._ctx_lock:
            if self._http_client_ctx is None:
                self._http_client_ctx = self._build_client_ctx(False)
            return self._http_client_ctx


def _read_exact(sock: socket.socket, n: int, peer: str = "",
                what: str = "") -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise FrameError(
                f"peer {peer or '?'} closed after {len(buf)}/{n} bytes"
                f"{f' reading {what}' if what else ''}"
            )
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, payload: bytes, peer: str = "",
                method: str = "") -> None:
    try:
        sock.sendall(_LEN.pack(len(payload)) + payload)
    except ConnectionError as e:
        raise FrameError(
            f"send of {len(payload)}B frame"
            f"{f' for {method}' if method else ''} to peer {peer or '?'} "
            f"failed: {e}"
        ) from e


def _recv_frame(sock: socket.socket, peer: str = "", method: str = "") -> bytes:
    what = f"{method} response" if method else "frame"
    (length,) = _LEN.unpack(_read_exact(sock, 4, peer, f"{what} header"))
    if length > MAX_FRAME:
        raise RPCError(
            f"frame too large: {length} "
            f"({what} from peer {peer or '?'})"
        )
    return _read_exact(sock, length, peer, f"{length}B {what}")


class RPCServer:
    """Dispatches "Noun.Verb" methods to registered handlers."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        region: str = "global",
        tls: Optional["TLSConfig"] = None,
    ) -> None:
        self.logger = logging.getLogger("nomad_tpu.rpc.server")
        self.tls = tls
        self.handlers: Dict[str, Callable[..., Any]] = {}
        # set to (host, port) of the leader for transparent forwarding
        self.leader_addr: Optional[Tuple[str, int]] = None
        self.is_leader: Callable[[], bool] = lambda: True
        self._forward_pool: Optional["RPCClient"] = None
        # cross-region federation (rpc.go:502 forwardRegion): resolves a
        # region name to that region's server RPC addrs, fed by gossip
        self.region = region
        self.region_servers: Optional[Callable[[str], list]] = None
        self._region_pools: Dict[Tuple[str, int], "RPCClient"] = {}
        self._region_pools_lock = threading.Lock()

        outer = self
        self._active_conns: set = set()
        self._active_lock = threading.Lock()
        self._stopping = False

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if outer.tls is not None:
                    try:
                        sock = outer.tls.server_context().wrap_socket(
                            sock, server_side=True
                        )
                    except (OSError, ssl.SSLError) as e:
                        outer.logger.debug("TLS handshake failed: %s", e)
                        return
                with outer._active_lock:
                    if outer._stopping:
                        # raced a stop(): close instead of serving — a
                        # dead server must not keep answering
                        try:
                            sock.close()
                        except OSError:
                            pass
                        return
                    outer._active_conns.add(sock)
                peer = "%s:%s" % self.client_address[:2]
                try:
                    while True:
                        frame = _recv_frame(sock, peer)
                        req = decode(frame)
                        resp = outer._dispatch(req)
                        out = encode(resp)
                        method = req.get("method", "")
                        if method in outer.handlers:
                            _record_frame_bytes(method, len(frame), len(out))
                        _send_frame(sock, out, peer, method)
                except (ConnectionError, OSError, ssl.SSLError):
                    pass
                finally:
                    with outer._active_lock:
                        outer._active_conns.discard(sock)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True
            # the watch serving bench parks thousands of persistent
            # watcher connections that dial in bursts; socketserver's
            # default backlog of 5 turns that storm into SYN drops and
            # client-side connect timeouts (kernel caps by somaxconn)
            request_queue_size = 1024

            def handle_error(self, request, client_address):
                # peer-side tear-downs stay quiet; anything else reaching
                # here escaped the handler's own guards and is a genuine
                # server bug — keep its full traceback, just via logging
                import ssl as ssl_mod
                import sys

                exc = sys.exc_info()[1]
                if isinstance(exc, (ConnectionError, ssl_mod.SSLError,
                                    TimeoutError, BrokenPipeError)):
                    outer.logger.debug(
                        "connection from %s dropped: %s", client_address, exc
                    )
                else:
                    outer.logger.warning(
                        "request from %s crashed", client_address,
                        exc_info=True,
                    )

        self._tcp = Server((host, port), Handler)
        self.addr: Tuple[str, int] = self._tcp.server_address
        self._thread: Optional[threading.Thread] = None

    def register(self, method: str, fn: Callable[..., Any]) -> None:
        self.handlers[method] = fn  # race-ok: endpoints register before serve() accepts connections

    def register_endpoint(self, noun: str, obj: object) -> None:
        """Every public method of ``obj`` becomes "<noun>.<method>"
        (the reference's endpoint struct registry, server.go:236)."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self.register(f"{noun}.{name}", fn)

    FORWARDED = "forwarded"
    LOCAL_ONLY = {"Status.ping", "Status.leader"}

    def _dispatch(self, req: dict) -> dict:
        seq = req.get("seq", 0)
        method = req.get("method", "")
        body = req.get("body", ())
        fn = self.handlers.get(method)
        if fn is None:
            # unregistered methods are NOT recorded: the per-method stats
            # table must stay bounded by the bind_server registry, not by
            # whatever strings a hostile peer mints
            return {"seq": seq, "error": f"unknown method {method!r}", "body": None}
        global _rpc_inflight
        with _rpc_lock:
            _rpc_inflight += 1
            inflight = _rpc_inflight
        metrics.set_gauge("nomad.rpc.inflight", inflight)
        t0 = time.monotonic()
        # re-enter the caller's trace: the server span is a child of the
        # client span that crossed the wire, so a forwarded write nests
        # client -> server(follower) -> client(forward) -> server(leader)
        token = xtrace.activate(req.get(TRACE_KEY))
        resp: dict
        try:
            with xtrace.span(f"rpc.server.{method}", kind="server",
                             attrs={"method": method}) as sattrs:
                try:
                    # region forwarding (rpc.go:502 forwardRegion): a
                    # request naming another region hops to any server
                    # there, which then applies its own leader forwarding
                    req_region = req.get("region")
                    if req_region and req_region != self.region:
                        result = self._forward_region(req_region, method, body)
                    # leader forwarding (rpc.go:409): followers proxy writes.
                    # "stale" is the allowStale read flag: the follower
                    # answers from its own FSM instead of forwarding, and
                    # the endpoint stamps measured follower_lag into
                    # QueryMeta (watch/stale.py)
                    elif (
                        not self.is_leader()
                        and self.leader_addr is not None
                        and self.leader_addr != self.addr
                        and method not in self.LOCAL_ONLY
                        and not req.get("no_forward")
                        and not req.get("stale")
                    ):
                        sattrs["forwarded"] = True
                        result = self._forward(method, body)
                    else:
                        if req.get("stale") and not self.is_leader():
                            sattrs["stale"] = True
                        result = fn(*body)
                    resp = {"seq": seq, "error": None, "body": result}
                except Exception as e:  # noqa: BLE001
                    sattrs["error"] = type(e).__name__
                    resp = {"seq": seq, "error": f"{type(e).__name__}: {e}",
                            "body": None}
        finally:
            xtrace.deactivate(token)
            with _rpc_lock:
                _rpc_inflight -= 1
                inflight = _rpc_inflight
            metrics.set_gauge("nomad.rpc.inflight", inflight)
        _record_dispatch(method, time.monotonic() - t0, resp["error"])
        return resp

    def _forward(self, method: str, body) -> Any:
        if self._forward_pool is None or self._forward_pool.addr != self.leader_addr:
            if self._forward_pool is not None:
                self._forward_pool.close()
            self._forward_pool = RPCClient(*self.leader_addr, tls=self.tls)
        return self._forward_pool.call(method, *body, no_forward=True)

    def _forward_region(self, region: str, method: str, body) -> Any:
        servers = self.region_servers(region) if self.region_servers else []
        if not servers:
            raise RPCError(f"no path to region {region!r}")
        addr = tuple(random.choice(servers))
        with self._region_pools_lock:
            pool = self._region_pools.get(addr)
            if pool is None:
                pool = self._region_pools[addr] = RPCClient(*addr, tls=self.tls)
        # keep the region tag: the remote sees its own region and serves it
        return pool.call(method, *body, region=region)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="rpc-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        # a stopped server must stop ANSWERING, not just accepting: close
        # established connections too, or clients pinned to a dead server
        # never observe the death (and never fail over)
        with self._active_lock:
            self._stopping = True
            conns = list(self._active_conns)
            self._active_conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._forward_pool is not None:
            self._forward_pool.close()
        with self._region_pools_lock:
            pools = list(self._region_pools.values())
            self._region_pools.clear()
        for pool in pools:
            pool.close()


class RPCClient:
    """Pooled client: one persistent connection, serialized calls
    (helper/pool ConnPool's role for a single peer)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 tls: Optional[TLSConfig] = None) -> None:
        self.addr = (host, port)
        self.timeout = timeout
        self.tls = tls
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seq = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.tls is not None:
                sni = (
                    self.tls.server_name
                    if self.tls.pin_server_name
                    else self.addr[0]
                )
                s = self.tls.client_context().wrap_socket(
                    s, server_hostname=sni
                )
            self._sock = s
        return self._sock

    def call(
        self,
        method: str,
        *args: Any,
        no_forward: bool = False,
        region: Optional[str] = None,
        timeout: Optional[float] = None,
        no_retry: bool = False,
        stale: bool = False,
    ) -> Any:
        """``timeout`` overrides the connection timeout for this call;
        ``no_retry`` disables the reconnect-resend (required for
        non-idempotent calls like Plan.Submit, where a resend would
        enqueue the work twice); ``stale`` marks an allowStale read the
        receiving replica serves locally instead of leader-forwarding
        (older peers ignore the unknown envelope field and forward as
        before — wire-compatible)."""
        peer = f"{self.addr[0]}:{self.addr[1]}"
        # the outbound span is opened BEFORE the envelope is built so
        # inject() carries this span's id: the server's handler span
        # becomes its child and the stitcher can pair the two to
        # estimate the clock offset between the processes
        with xtrace.span(f"rpc.client.{method}", kind="client",
                         attrs={"method": method, "peer": peer}) as attrs:
            with self._lock:
                self._seq += 1
                req = {"seq": self._seq, "method": method, "body": tuple(args)}
                if no_forward:
                    req["no_forward"] = True
                if region:
                    req["region"] = region
                if stale:
                    req["stale"] = True
                tctx = xtrace.inject()
                if tctx is not None:
                    req[TRACE_KEY] = tctx
                payload = encode(req)
                attrs["req_bytes"] = len(payload)
                try:
                    sock = self._connect()
                    if timeout is not None:
                        sock.settimeout(timeout)
                    try:
                        _send_frame(sock, payload, peer, method)
                        frame = _recv_frame(sock, peer, method)
                    finally:
                        if timeout is not None:
                            sock.settimeout(self.timeout)
                except (ConnectionError, OSError):
                    self._close_locked()
                    if no_retry:
                        raise
                    # one reconnect attempt (pool behavior on dead conns)
                    attrs["reconnected"] = True
                    sock = self._connect()
                    if timeout is not None:
                        sock.settimeout(timeout)
                    try:
                        _send_frame(sock, payload, peer, method)
                        frame = _recv_frame(sock, peer, method)
                    except (ConnectionError, OSError):
                        # a retry that dies mid-exchange leaves a request
                        # outstanding on this socket; keeping it would let
                        # the late response answer the NEXT call
                        self._close_locked()
                        raise
                    finally:
                        if timeout is not None:
                            try:
                                sock.settimeout(self.timeout)
                            except OSError:
                                pass
                attrs["resp_bytes"] = len(frame)
                resp = decode(frame)
                if resp.get("seq") != req["seq"]:
                    # late response from an abandoned exchange (e.g. a
                    # timeout that didn't tear the connection down) — it
                    # belongs to a PREVIOUS request, and every frame after
                    # it is off by one: poison, drop the connection
                    self._close_locked()
                    raise RPCError(
                        f"response seq mismatch for {method}: got "
                        f"{resp.get('seq')!r}, expected {req['seq']}"
                    )
            if resp.get("error"):
                raise RPCError(resp["error"])
            return resp.get("body")

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()


class LeaderConn:
    """Thread-safe cache of one RPCClient keyed on the (changing) leader
    address: get() reconnects when the address moves, close() tears down.
    Shared by everything that follows the leader (follower workers, the
    colocated-client failover proxy, RPC write forwarding)."""

    def __init__(self, timeout: float = 30.0,
                 tls: Optional[TLSConfig] = None) -> None:
        self.timeout = timeout
        self.tls = tls
        self._lock = threading.Lock()
        self._client: Optional[RPCClient] = None

    def get(self, addr) -> RPCClient:
        addr = tuple(addr)
        with self._lock:
            if self._client is not None and self._client.addr != addr:
                self._client.close()
                self._client = None
            if self._client is None:
                self._client = RPCClient(*addr, timeout=self.timeout, tls=self.tls)
            return self._client

    def close(self) -> None:
        with self._lock:
            if self._client is not None:
                self._client.close()
                self._client = None
