"""Scheduler (reference scheduler/): host parity pipeline + TPU engine entry."""
from .context import EvalContext, EvalEligibility  # noqa: F401
from .stack import GenericStack, SelectOptions, SystemStack  # noqa: F401
