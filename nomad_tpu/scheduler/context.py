"""Evaluation context and computed-class eligibility memoization.

Semantics follow reference ``scheduler/context.go`` (EvalContext :75,
ProposedAllocs :120, EvalEligibility :191).
"""
from __future__ import annotations

import enum
import logging
from typing import Dict, List, Optional

from ..structs.node_class import escaped_constraints
from ..structs.structs import Allocation, AllocMetric, Job, Plan
from ..structs.funcs import remove_allocs


class ComputedClassFeasibility(enum.Enum):
    UNKNOWN = 0
    INELIGIBLE = 1
    ELIGIBLE = 2
    ESCAPED = 3


class EvalEligibility:
    """Tracks per-computed-class eligibility over the course of an eval.

    This is the reference's key O(classes) << O(nodes) optimization; the TPU
    engine reuses it to compute feasibility masks per class and gather them
    per node.
    """

    def __init__(self) -> None:
        self.job: Dict[str, ComputedClassFeasibility] = {}
        self.job_escaped = False
        self.task_groups: Dict[str, Dict[str, ComputedClassFeasibility]] = {}
        self.tg_escaped_constraints: Dict[str, bool] = {}
        self.quota_reached = ""

    def set_job(self, job: Job) -> None:
        self.job_escaped = len(escaped_constraints(job.constraints)) != 0
        for tg in job.task_groups:
            constraints = list(tg.constraints)
            for task in tg.tasks:
                constraints.extend(task.constraints)
            self.tg_escaped_constraints[tg.name] = len(escaped_constraints(constraints)) != 0

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped_constraints.values())

    def get_classes(self) -> Dict[str, bool]:
        elig: Dict[str, bool] = {}
        for classes in self.task_groups.values():
            for cls, feas in classes.items():
                if feas == ComputedClassFeasibility.ELIGIBLE:
                    elig[cls] = True
                elif feas == ComputedClassFeasibility.INELIGIBLE:
                    elig.setdefault(cls, False)
        for cls, feas in self.job.items():
            if feas == ComputedClassFeasibility.ELIGIBLE:
                elig.setdefault(cls, True)
            elif feas == ComputedClassFeasibility.INELIGIBLE:
                elig[cls] = False
        return elig

    def job_status(self, cls: str) -> ComputedClassFeasibility:
        if self.job_escaped:
            return ComputedClassFeasibility.ESCAPED
        return self.job.get(cls, ComputedClassFeasibility.UNKNOWN)

    def set_job_eligibility(self, eligible: bool, cls: str) -> None:
        self.job[cls] = (
            ComputedClassFeasibility.ELIGIBLE if eligible else ComputedClassFeasibility.INELIGIBLE
        )

    def task_group_status(self, tg: str, cls: str) -> ComputedClassFeasibility:
        if self.tg_escaped_constraints.get(tg, False):
            return ComputedClassFeasibility.ESCAPED
        return self.task_groups.get(tg, {}).get(cls, ComputedClassFeasibility.UNKNOWN)

    def set_task_group_eligibility(self, eligible: bool, tg: str, cls: str) -> None:
        self.task_groups.setdefault(tg, {})[cls] = (
            ComputedClassFeasibility.ELIGIBLE if eligible else ComputedClassFeasibility.INELIGIBLE
        )

    def set_quota_limit_reached(self, quota: str) -> None:
        self.quota_reached = quota

    def quota_limit_reached(self) -> str:
        return self.quota_reached


class EvalContext:
    """Contextual state for one evaluation (state snapshot, plan, metrics)."""

    def __init__(self, state, plan: Plan, logger: Optional[logging.Logger] = None,
                 deterministic: bool = False, ring_seed: int = 0) -> None:
        self.state = state
        self.plan = plan
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler")
        self.metrics = AllocMetric()
        self.eligibility: Optional[EvalEligibility] = None
        # caches
        self.regexp_cache: Dict[str, object] = {}
        self.version_constraint_cache: Dict[str, object] = {}
        self.semver_constraint_cache: Dict[str, object] = {}
        # deterministic scheduling (no shuffle, lowest-index dynamic ports);
        # used by the host/TPU parity harness
        self.deterministic = deterministic
        # Per-node memoization across one eval's placements. The snapshot
        # is immutable for the eval's lifetime, so a node's proposed set
        # (and the NetworkIndex built from it) only changes when THIS
        # plan touches the node — keyed by the plan-shape token below.
        self._proposed_cache: Dict[str, tuple] = {}
        self._netidx_cache: Dict[str, tuple] = {}
        # Deterministic-mode analog of the reference's per-eval node
        # shuffle (stack.go:67 SetNodes -> util.go:329 shuffleNodes):
        # a per-eval starting offset for the candidate ring. Without it,
        # optimistically-concurrent evals sharing one snapshot walk
        # identical rings and collide at plan apply. 0 = insertion order
        # (the parity harness's fixed frame); same seed on the host stack
        # and the TPU scan keeps them plan-identical per eval.
        self.ring_seed = ring_seed

    def reset(self) -> None:
        self.metrics = AllocMetric()

    def _plan_token(self, node_id: str) -> tuple:
        """Shape of this plan's mutations for one node; any placement,
        eviction or preemption appended for the node changes a length
        and invalidates that node's cached proposed/NetworkIndex state."""
        return (
            len(self.plan.node_allocation.get(node_id, ())),
            len(self.plan.node_update.get(node_id, ())),
            len(self.plan.node_preemptions.get(node_id, ())),
        )

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """Existing non-terminal allocs - planned evictions - preemptions
        + planned placements (reference context.go:120), memoized per
        node for the duration of the eval (invalidated when the plan
        touches the node)."""
        from ..utils import phases as _phases

        token = self._plan_token(node_id)
        hit = self._proposed_cache.get(node_id)
        if hit is not None and hit[0] == token:
            return list(hit[1])
        with _phases.track("proposed"):
            existing = self.state.allocs_by_node_terminal(node_id, False)
            proposed = existing
            update = self.plan.node_update.get(node_id, [])
            if update:
                proposed = remove_allocs(existing, update)
            preempted = self.plan.node_preemptions.get(node_id, [])
            if preempted:
                proposed = remove_allocs(proposed, preempted)
            # Index by ID so in-place updates override rather than
            # double count.
            by_id = {a.id: a for a in proposed}
            for alloc in self.plan.node_allocation.get(node_id, []):
                by_id[alloc.id] = alloc
            out = list(by_id.values())
            self._proposed_cache[node_id] = (token, out)
            return list(out)

    def network_index(self, node, proposed: List[Allocation]):
        """Base NetworkIndex for ``node`` with ``proposed`` folded in,
        memoized like proposed_allocs; callers get a fork so their
        add_reserved calls never mutate the cached base. ``proposed``
        MUST be the ctx.proposed_allocs set for the node (the cache key
        assumes it)."""
        from ..structs.network import NetworkIndex

        token = self._plan_token(node.id)
        hit = self._netidx_cache.get(node.id)
        if hit is not None and hit[0] == token:
            return hit[1].fork()
        base = NetworkIndex(deterministic=self.deterministic)
        base.set_node(node)
        base.add_allocs(proposed)
        self._netidx_cache[node.id] = (token, base)
        return base.fork()

    def get_eligibility(self) -> EvalEligibility:
        if self.eligibility is None:
            self.eligibility = EvalEligibility()
        return self.eligibility
