"""Host-side feasibility checking.

This is the semantics oracle mirroring reference ``scheduler/feasible.go``:
each checker here corresponds 1:1 to a mask tensor in the TPU engine
(nomad_tpu/tpu/engine.py). StaticIterator :44, HostVolumeChecker :102,
DriverChecker :182, DistinctHostsIterator :254, DistinctPropertyIterator
:353, ConstraintChecker :458, checkConstraint :534, FeasibilityWrapper :778,
DeviceChecker :893.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..structs.structs import (
    CONSTRAINT_ATTRIBUTE_IS_NOT_SET,
    CONSTRAINT_ATTRIBUTE_IS_SET,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER,
    CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL,
    CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
    VOLUME_TYPE_HOST,
    Constraint,
    Job,
    Node,
    NodeDeviceResource,
    RequestedDevice,
    TaskGroup,
    VolumeRequest,
)
from .context import ComputedClassFeasibility, EvalContext
from .versions import Constraints as VersionConstraints, Version
from .util import shuffle_nodes


# ---------------------------------------------------------------------------
# Target resolution / constraint evaluation
# ---------------------------------------------------------------------------


def resolve_target(target: str, node: Node) -> Tuple[Any, bool]:
    """Resolve ``${node.*}`` / ``${attr.*}`` / ``${meta.*}`` interpolations;
    a non-interpolated target is a literal (reference feasible.go:497)."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        attr = target[len("${attr.") : -1]
        if attr in node.attributes:
            return node.attributes[attr], True
        return None, False
    if target.startswith("${meta."):
        meta = target[len("${meta.") : -1]
        if meta in node.meta:
            return node.meta[meta], True
        return None, False
    return None, False


def check_lexical_order(op: str, lval: Any, rval: Any) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    if op == "<":
        return lval < rval
    if op == "<=":
        return lval <= rval
    if op == ">":
        return lval > rval
    if op == ">=":
        return lval >= rval
    return False


def check_version_match(ctx: EvalContext, lval: Any, rval: Any, strict: bool) -> bool:
    if isinstance(lval, int):
        lval = str(lval)
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    # The version value itself is always leniently parsed; only the
    # constraint syntax differs between version/semver (reference semver.go).
    vers = Version.parse(lval, strict=strict)
    if vers is None:
        return False
    cache = ctx.semver_constraint_cache if strict else ctx.version_constraint_cache
    cons = cache.get(rval)
    if cons is None:
        cons = VersionConstraints.parse(rval, strict=strict)
        if cons is None:
            return False
        cache[rval] = cons
    return cons.check(vers)


def check_regexp_match(ctx: EvalContext, lval: Any, rval: Any) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    regex = ctx.regexp_cache.get(rval)
    if regex is None:
        try:
            regex = re.compile(rval)
        except re.error:
            return False
        ctx.regexp_cache[rval] = regex
    return regex.search(lval) is not None


def _split_set(s: str) -> List[str]:
    return [p.strip() for p in s.split(",")]


def check_set_contains_all(lval: Any, rval: Any) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    have = set(_split_set(lval))
    return all(item in have for item in _split_set(rval))


def check_set_contains_any(lval: Any, rval: Any) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    have = set(_split_set(lval))
    return any(item in have for item in _split_set(rval))


def check_constraint(
    ctx: EvalContext, operand: str, lval: Any, rval: Any, lfound: bool, rfound: bool
) -> bool:
    """Reference feasible.go:534 — full operand table."""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True
    if operand in ("=", "==", "is"):
        return lfound and rfound and lval == rval
    if operand in ("!=", "not"):
        return lval != rval
    if operand in ("<", "<=", ">", ">="):
        return lfound and rfound and check_lexical_order(operand, lval, rval)
    if operand == CONSTRAINT_ATTRIBUTE_IS_SET:
        return lfound
    if operand == CONSTRAINT_ATTRIBUTE_IS_NOT_SET:
        return not lfound
    if operand == CONSTRAINT_VERSION:
        return lfound and rfound and check_version_match(ctx, lval, rval, strict=False)
    if operand == CONSTRAINT_SEMVER:
        return lfound and rfound and check_version_match(ctx, lval, rval, strict=True)
    if operand == CONSTRAINT_REGEX:
        return lfound and rfound and check_regexp_match(ctx, lval, rval)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return lfound and rfound and check_set_contains_all(lval, rval)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return lfound and rfound and check_set_contains_any(lval, rval)
    return False


def check_affinity(ctx, operand, lval, rval, lfound, rfound) -> bool:
    return check_constraint(ctx, operand, lval, rval, lfound, rfound)


def matches_affinity(ctx: EvalContext, affinity, node: Node) -> bool:
    lval, lok = resolve_target(affinity.ltarget, node)
    rval, rok = resolve_target(affinity.rtarget, node)
    return check_affinity(ctx, affinity.operand, lval, rval, lok, rok)


# ---------------------------------------------------------------------------
# Device attribute constraints (reference feasible.go:1054)
# ---------------------------------------------------------------------------


def resolve_device_target(target: str, d: NodeDeviceResource) -> Tuple[Any, bool]:
    if not target.startswith("${"):
        return _parse_attribute(target), True
    if target == "${device.model}":
        return d.name, True
    if target == "${device.vendor}":
        return d.vendor, True
    if target == "${device.type}":
        return d.type, True
    if target.startswith("${device.attr."):
        attr = target[len("${device.attr.") : -1]
        if attr in d.attributes:
            return d.attributes[attr], True
        return None, False
    return None, False


def _parse_attribute(s: str) -> Any:
    try:
        return int(s)
    except (TypeError, ValueError):
        pass
    try:
        return float(s)
    except (TypeError, ValueError):
        pass
    if isinstance(s, str):
        if s.lower() == "true":
            return True
        if s.lower() == "false":
            return False
    return s


def _attr_compare(lval: Any, rval: Any) -> Optional[int]:
    """Typed comparison; None if the types aren't comparable."""
    if isinstance(lval, bool) != isinstance(rval, bool):
        return None
    if isinstance(lval, (int, float)) and isinstance(rval, (int, float)):
        return (lval > rval) - (lval < rval)
    if isinstance(lval, str) and isinstance(rval, str):
        return (lval > rval) - (lval < rval)
    if isinstance(lval, bool) and isinstance(rval, bool):
        return (lval > rval) - (lval < rval)
    return None


def check_attribute_constraint(
    ctx: EvalContext, operand: str, lval: Any, rval: Any, lfound: bool, rfound: bool
) -> bool:
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True
    if operand in ("!=", "not"):
        if not (lfound or rfound):
            return False
        if lfound != rfound:
            return True
        v = _attr_compare(lval, rval)
        return v is not None and v != 0
    if operand in ("<", "<=", ">", ">=", "=", "==", "is"):
        if not (lfound and rfound):
            return False
        v = _attr_compare(lval, rval)
        if v is None:
            return False
        return {
            "is": v == 0, "==": v == 0, "=": v == 0,
            "<": v < 0, "<=": v <= 0, ">": v > 0, ">=": v >= 0,
        }[operand]
    if operand in (CONSTRAINT_VERSION, CONSTRAINT_SEMVER):
        if not (lfound and rfound):
            return False
        return check_version_match(ctx, str(lval), str(rval), strict=operand == CONSTRAINT_SEMVER)
    if operand == CONSTRAINT_REGEX:
        if not (lfound and rfound):
            return False
        return isinstance(lval, str) and isinstance(rval, str) and check_regexp_match(ctx, lval, rval)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return lfound and rfound and check_set_contains_all(lval, rval)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return lfound and rfound and check_set_contains_any(lval, rval)
    if operand == CONSTRAINT_ATTRIBUTE_IS_SET:
        return lfound
    if operand == CONSTRAINT_ATTRIBUTE_IS_NOT_SET:
        return not lfound
    return False


def check_attribute_affinity(ctx, operand, lval, rval, lfound, rfound) -> bool:
    return check_attribute_constraint(ctx, operand, lval, rval, lfound, rfound)


def node_device_matches(ctx: EvalContext, d: NodeDeviceResource, req: RequestedDevice) -> bool:
    """Reference feasible.go:998 — id match plus attr constraints (no count)."""
    if not d.id().matches(req.id()):
        return False
    for c in req.constraints:
        lval, lok = resolve_device_target(c.ltarget, d)
        rval, rok = resolve_device_target(c.rtarget, d)
        if not check_attribute_constraint(ctx, c.operand, lval, rval, lok, rok):
            return False
    return True


# ---------------------------------------------------------------------------
# Source iterators
# ---------------------------------------------------------------------------


class StaticIterator:
    """Yields nodes in fixed order; Reset() replays from the start of the
    ring so every node is seen at most once per pass (feasible.go:44)."""

    def __init__(self, ctx: EvalContext, nodes: Optional[List[Node]]) -> None:
        self.ctx = ctx
        self.nodes: List[Node] = nodes or []
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.evaluate_node()
        return option

    def reset(self) -> None:
        self.seen = 0

    def set_nodes(self, nodes: List[Node]) -> None:
        self.nodes = nodes
        self.offset = 0
        self.seen = 0


def new_random_iterator(ctx: EvalContext, nodes: List[Node]) -> StaticIterator:
    if not ctx.deterministic:
        shuffle_nodes(nodes)
    return StaticIterator(ctx, nodes)


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------


class HostVolumeChecker:
    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.volumes: Dict[str, List[VolumeRequest]] = {}

    def set_volumes(self, volumes: Dict[str, VolumeRequest]) -> None:
        lookup: Dict[str, List[VolumeRequest]] = {}
        for req in volumes.values():
            if req.type != VOLUME_TYPE_HOST:
                continue
            lookup.setdefault(req.source, []).append(req)
        self.volumes = lookup

    def feasible(self, node: Node) -> bool:
        if self._has_volumes(node):
            return True
        self.ctx.metrics.filter_node(node, "missing compatible host volumes")
        return False

    def _has_volumes(self, node: Node) -> bool:
        if not self.volumes:
            return True
        if len(self.volumes) > len(node.host_volumes):
            return False
        for source, requests in self.volumes.items():
            vol = node.host_volumes.get(source)
            if vol is None:
                return False
            if not vol.read_only:
                continue
            if any(not req.read_only for req in requests):
                return False
        return True


class DriverChecker:
    def __init__(self, ctx: EvalContext, drivers: Optional[Iterable[str]] = None) -> None:
        self.ctx = ctx
        self.drivers = set(drivers or ())

    def set_drivers(self, drivers: Iterable[str]) -> None:
        self.drivers = set(drivers)

    def feasible(self, node: Node) -> bool:
        if self._has_drivers(node):
            return True
        self.ctx.metrics.filter_node(node, "missing drivers")
        return False

    def _has_drivers(self, node: Node) -> bool:
        for driver in self.drivers:
            info = node.drivers.get(driver)
            if info is not None:
                if info.detected and info.healthy:
                    continue
                return False
            value = node.attributes.get(f"driver.{driver}")
            if value is None:
                return False
            if str(value).lower() not in ("1", "true"):
                return False
        return True


class ConstraintChecker:
    def __init__(self, ctx: EvalContext, constraints: Optional[List[Constraint]] = None) -> None:
        self.ctx = ctx
        self.constraints = constraints or []

    def set_constraints(self, constraints: List[Constraint]) -> None:
        self.constraints = constraints

    def feasible(self, node: Node) -> bool:
        for constraint in self.constraints:
            if not self._meets_constraint(constraint, node):
                self.ctx.metrics.filter_node(node, str(constraint))
                return False
        return True

    def _meets_constraint(self, constraint: Constraint, node: Node) -> bool:
        lval, lok = resolve_target(constraint.ltarget, node)
        rval, rok = resolve_target(constraint.rtarget, node)
        return check_constraint(self.ctx, constraint.operand, lval, rval, lok, rok)


class DeviceChecker:
    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.required: List[RequestedDevice] = []

    def set_task_group(self, tg: TaskGroup) -> None:
        self.required = []
        for task in tg.tasks:
            self.required.extend(task.resources.devices)

    def feasible(self, node: Node) -> bool:
        if self._has_devices(node):
            return True
        self.ctx.metrics.filter_node(node, "missing devices")
        return False

    def _has_devices(self, node: Node) -> bool:
        if not self.required:
            return True
        node_devs = node.node_resources.devices
        if not node_devs:
            return False
        available = {}
        for d in node_devs:
            healthy = sum(1 for inst in d.instances if inst.healthy)
            if healthy:
                available[id(d)] = (d, healthy)
        for req in self.required:
            matched = False
            for key, (d, unused) in available.items():
                if unused == 0 or unused < req.count:
                    continue
                if node_device_matches(self.ctx, d, req):
                    available[key] = (d, unused - req.count)
                    matched = True
                    break
            if not matched:
                return False
        return True


# ---------------------------------------------------------------------------
# Distinct hosts / distinct property iterators
# ---------------------------------------------------------------------------


class DistinctHostsIterator:
    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.tg: Optional[TaskGroup] = None
        self.job: Optional[Job] = None
        self.tg_distinct_hosts = False
        self.job_distinct_hosts = False

    @staticmethod
    def _has_distinct_hosts(constraints: List[Constraint]) -> bool:
        return any(c.operand == CONSTRAINT_DISTINCT_HOSTS for c in constraints)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        self.tg_distinct_hosts = self._has_distinct_hosts(tg.constraints)

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_distinct_hosts = self._has_distinct_hosts(job.constraints)

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not (self.job_distinct_hosts or self.tg_distinct_hosts):
                return option
            if not self._satisfies(option):
                self.ctx.metrics.filter_node(option, CONSTRAINT_DISTINCT_HOSTS)
                continue
            return option

    def _satisfies(self, option: Node) -> bool:
        proposed = self.ctx.proposed_allocs(option.id)
        for alloc in proposed:
            job_collision = alloc.job_id == self.job.id
            task_collision = alloc.task_group == self.tg.name
            if (self.job_distinct_hosts and job_collision) or (job_collision and task_collision):
                return False
        return True

    def reset(self) -> None:
        self.source.reset()


class DistinctPropertyIterator:
    def __init__(self, ctx: EvalContext, source) -> None:
        from .propertyset import PropertySet

        self.ctx = ctx
        self.source = source
        self.tg: Optional[TaskGroup] = None
        self.job: Optional[Job] = None
        self.has_distinct_property_constraints = False
        self.job_property_sets: List = []
        self.group_property_sets: Dict[str, List] = {}
        self._PropertySet = PropertySet

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets = []
            for c in tg.constraints:
                if c.operand != CONSTRAINT_DISTINCT_PROPERTY:
                    continue
                pset = self._PropertySet(self.ctx, self.job)
                pset.set_tg_constraint(c, tg.name)
                sets.append(pset)
            self.group_property_sets[tg.name] = sets
        self.has_distinct_property_constraints = bool(
            self.job_property_sets or self.group_property_sets[tg.name]
        )

    def set_job(self, job: Job) -> None:
        self.job = job
        for c in job.constraints:
            if c.operand != CONSTRAINT_DISTINCT_PROPERTY:
                continue
            pset = self._PropertySet(self.ctx, job)
            pset.set_job_constraint(c)
            self.job_property_sets.append(pset)

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not self.has_distinct_property_constraints:
                return option
            if not self._satisfies(option, self.job_property_sets):
                continue
            if not self._satisfies(option, self.group_property_sets.get(self.tg.name, [])):
                continue
            return option

    def _satisfies(self, option: Node, psets) -> bool:
        for ps in psets:
            satisfies, reason = ps.satisfies_distinct_properties(option, self.tg.name)
            if not satisfies:
                self.ctx.metrics.filter_node(option, reason)
                return False
        return True

    def reset(self) -> None:
        self.source.reset()
        for ps in self.job_property_sets:
            ps.populate_proposed()
        for sets in self.group_property_sets.values():
            for ps in sets:
                ps.populate_proposed()


# ---------------------------------------------------------------------------
# Feasibility wrapper with computed-class memoization
# ---------------------------------------------------------------------------


class FeasibilityWrapper:
    """Skips per-node checks when the node's computed class is already known
    eligible/ineligible (reference feasible.go:778)."""

    def __init__(self, ctx: EvalContext, source, job_checkers, tg_checkers) -> None:
        self.ctx = ctx
        self.source = source
        self.job_checkers = job_checkers
        self.tg_checkers = tg_checkers
        self.tg = ""

    def set_task_group(self, tg_name: str) -> None:
        self.tg = tg_name

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[Node]:
        elig = self.ctx.get_eligibility()
        metrics = self.ctx.metrics
        while True:
            option = self.source.next()
            if option is None:
                return None

            job_escaped = job_unknown = False
            status = elig.job_status(option.computed_class)
            if status == ComputedClassFeasibility.INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == ComputedClassFeasibility.ESCAPED:
                job_escaped = True
            elif status == ComputedClassFeasibility.UNKNOWN:
                job_unknown = True

            # fast-path a known-ELIGIBLE class: the job checkers already
            # passed for this class, don't re-run them per node
            # (reference feasible.go:808 eEligible case)
            if status != ComputedClassFeasibility.ELIGIBLE:
                failed_job = False
                for check in self.job_checkers:
                    if not check.feasible(option):
                        if not job_escaped:
                            elig.set_job_eligibility(False, option.computed_class)
                        failed_job = True
                        break
                if failed_job:
                    continue
                if not job_escaped and job_unknown:
                    elig.set_job_eligibility(True, option.computed_class)

            tg_escaped = tg_unknown = False
            status = elig.task_group_status(self.tg, option.computed_class)
            if status == ComputedClassFeasibility.INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == ComputedClassFeasibility.ELIGIBLE:
                return option
            elif status == ComputedClassFeasibility.ESCAPED:
                tg_escaped = True
            elif status == ComputedClassFeasibility.UNKNOWN:
                tg_unknown = True

            failed_tg = False
            for check in self.tg_checkers:
                if not check.feasible(option):
                    if not tg_escaped:
                        elig.set_task_group_eligibility(False, self.tg, option.computed_class)
                    failed_tg = True
                    break
            if failed_tg:
                continue
            if not tg_escaped and tg_unknown:
                elig.set_task_group_eligibility(True, self.tg, option.computed_class)
            return option


class QuotaIterator:
    """OSS pass-through (quotas are an enterprise feature in the reference)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.source = source

    def next(self) -> Optional[Node]:
        return self.source.next()

    def reset(self) -> None:
        self.source.reset()
