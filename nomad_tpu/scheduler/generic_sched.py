"""GenericScheduler: service and batch evaluation processing.

Semantics follow reference ``scheduler/generic_sched.go`` — Process :122,
process :212, computeJobAllocs :323, computePlacements :426,
findPreferredNode :630. The placement backend is pluggable: ``binpack``
walks the host iterator stack per placement; ``tpu_binpack`` batches all
placements for the eval through the JAX engine (nomad_tpu/tpu/engine.py).
"""
from __future__ import annotations

import logging
import time as _time
from typing import Dict, List, Optional

from ..structs.structs import (
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_RUN,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_ALLOC_STOP,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    EVAL_TRIGGER_FAILED_FOLLOW_UP,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_MAX_PLANS,
    EVAL_TRIGGER_NODE_DRAIN,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_PERIODIC_JOB,
    EVAL_TRIGGER_PREEMPTION,
    EVAL_TRIGGER_QUEUED_ALLOCS,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    EVAL_TRIGGER_ROLLING_UPDATE,
    SCHED_ALG_TPU_BINPACK,
    SCHED_ALG_TPU_BINPACK_CHUNKED,
    AllocMetric,
    AllocatedResources,
    AllocatedSharedResources,
    Allocation,
    Evaluation,
    Node,
    RescheduleEvent,
    RescheduleTracker,
    deployment_get_id,
)
from ..trace import lifecycle as _trace_lc
from .context import EvalContext
from .reconcile import AllocReconciler
from .reconcile_util import AllocPlaceResult
from .stack import GenericStack, SelectOptions
from .util import (
    BLOCKED_EVAL_FAILED_PLACEMENTS,
    BLOCKED_EVAL_MAX_PLAN_DESC,
    MAX_PAST_RESCHEDULE_EVENTS,
    SetStatusError,
    adjust_queued_allocations,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    tasks_updated,
    update_non_terminal_allocs_to_lost,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

_VALID_TRIGGERS = {
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_NODE_DRAIN,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_ALLOC_STOP,
    EVAL_TRIGGER_ROLLING_UPDATE,
    EVAL_TRIGGER_QUEUED_ALLOCS,
    EVAL_TRIGGER_PERIODIC_JOB,
    EVAL_TRIGGER_MAX_PLANS,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    EVAL_TRIGGER_FAILED_FOLLOW_UP,
    EVAL_TRIGGER_PREEMPTION,
}


class GenericScheduler:
    def __init__(self, logger, state, planner, batch: bool,
                 deterministic: bool = False) -> None:
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler.generic")
        self.state = state
        self.planner = planner
        self.batch = batch
        self.deterministic = deterministic
        # per-eval candidate-ring seeding in deterministic mode (the
        # reference's shuffle analog; EvalContext.ring_seed). Off by
        # default so the parity harness keeps its fixed insertion-order
        # frame; the production server turns it on.
        self.ring_decorrelate = False
        # evals below this placement count skip the device and run the
        # host iterator stack (engine.compute_placements); 0 = always
        # device. Set by the production server.
        self.device_min_placements = 0

        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None
        self.followup_evals: List[Evaluation] = []
        self.deployment = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, AllocMetric]] = None
        self.queued_allocs: Dict[str, int] = {}

    # ------------------------------------------------------------------

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation

        if evaluation.triggered_by not in _VALID_TRIGGERS:
            desc = f"scheduler cannot handle '{evaluation.triggered_by}' evaluation reason"
            set_status(
                self.logger, self.planner, self.eval, None, self.blocked,
                self.failed_tg_allocs, EVAL_STATUS_FAILED, desc, self.queued_allocs,
                deployment_get_id(self.deployment),
            )
            return

        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else MAX_SERVICE_SCHEDULE_ATTEMPTS
        try:
            retry_max(limit, self._process, lambda: progress_made(self.plan_result))
        except SetStatusError as err:
            # Max plan attempts: blocked eval so we retry when capacity frees.
            self._create_blocked_eval(plan_failure=True)
            set_status(
                self.logger, self.planner, self.eval, None, self.blocked,
                self.failed_tg_allocs, err.eval_status, str(err), self.queued_allocs,
                deployment_get_id(self.deployment),
            )
            return

        if self.eval.status == EVAL_STATUS_BLOCKED and self.failed_tg_allocs:
            e = self.ctx.get_eligibility()
            new_eval = self.eval.copy()
            new_eval.escaped_computed_class = e.has_escaped()
            new_eval.class_eligibility = e.get_classes()
            new_eval.quota_limit_reached = e.quota_limit_reached()
            self.planner.reblock_eval(new_eval)
            return

        set_status(
            self.logger, self.planner, self.eval, None, self.blocked,
            self.failed_tg_allocs, EVAL_STATUS_COMPLETE, "", self.queued_allocs,
            deployment_get_id(self.deployment),
        )

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        e = self.ctx.get_eligibility()
        escaped = e.has_escaped()
        class_eligibility = None if escaped else e.get_classes()
        self.blocked = self.eval.create_blocked_eval(
            class_eligibility, escaped, e.quota_limit_reached()
        )
        if plan_failure:
            self.blocked.triggered_by = EVAL_TRIGGER_MAX_PLANS
            self.blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
        else:
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    # ------------------------------------------------------------------

    def _process(self) -> bool:
        self.job = self.state.job_by_id(self.eval.namespace, self.eval.job_id)

        self.queued_allocs = {}
        self.followup_evals = []
        self.plan = self.eval.make_plan(self.job)

        if not self.batch:
            self.deployment = self.state.latest_deployment_by_job_id(
                self.eval.namespace, self.eval.job_id
            )

        self.failed_tg_allocs = None
        ring_seed = 0
        if self.deterministic and self.ring_decorrelate:
            import zlib

            ring_seed = zlib.crc32(self.eval.id.encode()) & 0x7FFFFFFF
        self.ctx = EvalContext(self.state, self.plan, self.logger,
                               deterministic=self.deterministic,
                               ring_seed=ring_seed)
        self.stack = GenericStack(self.batch, self.ctx)
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if (
            self.eval.status != EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
            and self.blocked is None
        ):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_noop() and not self.eval.annotate_plan:
            return True

        for followup in self.followup_evals:
            followup.previous_eval = self.eval.id
            self.planner.create_eval(followup)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug(
                "plan didn't fully commit: attempted %d placed %d", expected, actual
            )
            # A partial commit without a state refresh means we'd retry
            # against the same stale data forever.
            raise RuntimeError("missing state refresh after partial commit")

        return True

    # ------------------------------------------------------------------

    def _compute_job_allocs(self) -> None:
        # reconcile tracked separately from placement: placement blocks
        # on the device dispatch and must not pollute host-phase shares.
        # The host-work semaphore parks excess worker threads (GIL
        # convoy guard — utils/hostwork.py); it is released before
        # placement, which may block on the batched device dispatch.
        from ..utils import phases as _phases
        from ..utils.hostwork import HOST_WORK_SEM

        with HOST_WORK_SEM:
            with _phases.track("reconcile"):
                results = self._reconcile_job_allocs()
        if results is not None:
            self._compute_placements(results.destructive_update, results.place)

    def _reconcile_job_allocs(self):
        allocs = self.state.allocs_by_job(self.eval.namespace, self.eval.job_id, True)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        reconciler = AllocReconciler(
            self.logger,
            self._generic_alloc_update_fn(),
            self.batch,
            self.eval.job_id,
            self.job,
            self.deployment,
            allocs,
            tainted,
            self.eval.id,
        )
        from ..utils import metrics as _metrics

        _t0 = _metrics.now()
        results = reconciler.compute()
        _metrics.measure_since("nomad.sched.reconcile", _t0)

        if self.eval.annotate_plan:
            from ..structs.structs import PlanAnnotations

            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=results.desired_tg_updates
            )

        self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        for evals in results.desired_followup_evals.values():
            self.followup_evals.extend(evals)

        if results.deployment is not None:
            self.deployment = results.deployment

        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status
            )

        for update in results.inplace_update:
            if update.deployment_id != deployment_get_id(self.deployment):
                update.deployment_id = deployment_get_id(self.deployment)
                update.deployment_status = None
            self.plan.append_alloc(update)

        for update in results.attribute_updates.values():
            self.plan.append_alloc(update)

        if not results.place and not results.destructive_update:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return None

        for place in results.place:
            self.queued_allocs[place.task_group.name] = (
                self.queued_allocs.get(place.task_group.name, 0) + 1
            )
        for destructive in results.destructive_update:
            self.queued_allocs[destructive.place_task_group.name] = (
                self.queued_allocs.get(destructive.place_task_group.name, 0) + 1
            )

        return results

    # ------------------------------------------------------------------

    def _compute_placements(self, destructive: List, place: List) -> None:
        nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
        deployment_id = ""
        if self.deployment is not None and self.deployment.active():
            deployment_id = self.deployment.id

        self.stack.set_nodes(nodes)
        self._nodes_by_dc = by_dc

        # tpu_binpack: batch the whole placement list through one device scan.
        # tpu_binpack_chunked: same entry, but the engine may run the eval
        # through the chunked top-K throughput tier (sampled parity) when
        # it is chunk-eligible; ineligible evals — preempting, destructive,
        # int-mode — take the bit-parity scan exactly as tpu_binpack.
        _, sched_config = self.state.scheduler_config()
        if sched_config is not None and sched_config.scheduler_algorithm in (
            SCHED_ALG_TPU_BINPACK,
            SCHED_ALG_TPU_BINPACK_CHUNKED,
        ):
            from ..tpu.integration import compute_placements_with_engine

            self.chunked_tier = (
                sched_config.scheduler_algorithm == SCHED_ALG_TPU_BINPACK_CHUNKED
            )
            self.chunk_k = getattr(sched_config, "chunk_k", 128)
            self.parity_sample_rate = getattr(
                sched_config, "parity_sample_rate", 0.0
            )
            if compute_placements_with_engine(self, destructive, place) is True:
                _trace_lc.set_path(self.eval.id, "device")
                # device-built plan: eligible for the async eval-lifecycle
                # pipeline (the worker may hand commit + ack to the async
                # applier instead of blocking on the plan future)
                self.plan.async_ok = True
                return

        # falling through = the python iterator stack places this eval
        # (small-eval gate, unsupported features, or host algorithm)
        _trace_lc.set_path(self.eval.id, "host")

        from ..utils import phases as _phases

        with _phases.track("place"):
            self._host_placement_loop(destructive, place, by_dc,
                                      deployment_id)

    def _host_placement_loop(self, destructive: List, place: List,
                             by_dc, deployment_id: str) -> None:
        now = _time.time_ns()

        # Config-gated preemption for generic (service/batch) evals: the
        # same switch the device encode consults, so host fallback and
        # device scan agree on whether this eval may evict.
        from .preemption import preemption_enabled

        _, sched_config = self.state.scheduler_config()
        preempt = preemption_enabled(sched_config, self.job.type)

        # Destructive before place: their resources must be discounted first.
        for results in (destructive, place):
            for missing in results:
                tg = missing.get_task_group()

                if self.failed_tg_allocs and tg.name in self.failed_tg_allocs:
                    self.failed_tg_allocs[tg.name].coalesced_failures += 1
                    continue

                preferred_node = self._find_preferred_node(missing)

                stop_prev_alloc, stop_prev_alloc_desc = missing.stop_previous_alloc()
                prev_allocation = missing.get_previous_allocation()
                if stop_prev_alloc:
                    self.plan.append_stopped_alloc(prev_allocation, stop_prev_alloc_desc, "")

                select_options = get_select_options(
                    prev_allocation, preferred_node, preempt=preempt
                )
                option = self.select_next_option(tg, select_options)

                self.ctx.metrics.nodes_available = by_dc
                self.ctx.metrics.populate_score_meta_data()

                if option is not None:
                    resources = AllocatedResources(
                        tasks=dict(option.task_resources),
                        shared=AllocatedSharedResources(disk_mb=tg.ephemeral_disk.size_mb),
                    )
                    if option.alloc_resources is not None:
                        resources.shared.networks = option.alloc_resources.networks

                    alloc = Allocation(
                        namespace=self.job.namespace,
                        eval_id=self.eval.id,
                        name=missing.get_name(),
                        job_id=self.job.id,
                        task_group=tg.name,
                        metrics=self.ctx.metrics,
                        node_id=option.node.id,
                        node_name=option.node.name,
                        deployment_id=deployment_id,
                        allocated_resources=resources,
                        desired_status=ALLOC_DESIRED_RUN,
                        client_status=ALLOC_CLIENT_PENDING,
                    )

                    if prev_allocation is not None:
                        alloc.previous_allocation = prev_allocation.id
                        if missing.is_rescheduling():
                            update_reschedule_tracker(alloc, prev_allocation, now)

                    if missing.is_canary() and self.deployment is not None:
                        state = self.deployment.task_groups.get(tg.name)
                        if state is not None:
                            state.placed_canaries.append(alloc.id)
                        from ..structs.structs import AllocDeploymentStatus

                        alloc.deployment_status = AllocDeploymentStatus(canary=True)

                    self._handle_preemptions(option, alloc, missing)
                    self.plan.append_alloc(alloc)
                else:
                    if self.failed_tg_allocs is None:
                        self.failed_tg_allocs = {}
                    self.failed_tg_allocs[tg.name] = self.ctx.metrics
                    if stop_prev_alloc:
                        self.plan.pop_update(prev_allocation)

    def select_next_option(self, tg, select_options: SelectOptions):
        """Host placement backend (subclass/monkeypatch point for tests)."""
        return self.stack.select(tg, select_options)

    def _handle_preemptions(self, option, alloc: Allocation, missing) -> None:
        if option.preempted_allocs is None:
            return
        preempted_ids = []
        for stop in option.preempted_allocs:
            self.plan.append_preempted_alloc(stop, alloc.id)
            preempted_ids.append(stop.id)
        alloc.preempted_allocations = preempted_ids

    def _find_preferred_node(self, place) -> Optional[Node]:
        prev = place.get_previous_allocation()
        if prev is not None and place.get_task_group().ephemeral_disk.sticky:
            preferred = self.state.node_by_id(prev.node_id)
            if preferred is not None and preferred.ready():
                return preferred
        return None

    def _generic_alloc_update_fn(self):
        """Reference util.go:944 genericAllocUpdateFn."""

        def update_fn(existing: Allocation, new_job, new_tg):
            if existing.job is not None and existing.job.job_modify_index == new_job.job_modify_index:
                return True, False, None
            if existing.job is None or tasks_updated(new_job, existing.job, new_tg.name):
                return False, True, None
            if existing.terminal_status():
                return True, False, None

            node = self.state.node_by_id(existing.node_id)
            if node is None:
                return False, True, None

            from .util import ALLOC_IN_PLACE

            self.stack.set_nodes([node])
            self.ctx.plan.append_stopped_alloc(existing, ALLOC_IN_PLACE, "")
            option = self.stack.select(new_tg, None)
            self.ctx.plan.pop_update(existing)

            if option is None:
                return False, True, None

            for task, resources in option.task_resources.items():
                networks = []
                if existing.allocated_resources is not None:
                    tr = existing.allocated_resources.tasks.get(task)
                    if tr is not None:
                        networks = tr.networks
                resources.networks = networks

            new_alloc = existing.copy_skip_job()
            new_alloc.eval_id = self.eval.id
            new_alloc.job = None
            new_alloc.allocated_resources = AllocatedResources(
                tasks=dict(option.task_resources),
                shared=AllocatedSharedResources(
                    disk_mb=new_tg.ephemeral_disk.size_mb,
                    networks=(
                        existing.allocated_resources.shared.networks
                        if existing.allocated_resources is not None
                        else []
                    ),
                ),
            )
            new_alloc.metrics = existing.metrics.copy() if existing.metrics else AllocMetric()
            return False, False, new_alloc

        return update_fn


def get_select_options(prev_allocation: Optional[Allocation], preferred_node,
                       preempt: bool = False) -> SelectOptions:
    options = SelectOptions(preempt=preempt)
    if prev_allocation is not None:
        penalty = set()
        if prev_allocation.client_status == ALLOC_CLIENT_FAILED:
            penalty.add(prev_allocation.node_id)
        if prev_allocation.reschedule_tracker is not None:
            for ev in prev_allocation.reschedule_tracker.events:
                penalty.add(ev.prev_node_id)
        options.penalty_node_ids = penalty
    if preferred_node is not None:
        options.preferred_nodes = [preferred_node]
    return options


def update_reschedule_tracker(alloc: Allocation, prev: Allocation, now_ns: int) -> None:
    """Carry over in-window reschedule events and append this one."""
    policy = prev.reschedule_policy()
    events: List[RescheduleEvent] = []
    if prev.reschedule_tracker is not None:
        interval = policy.interval_ns if policy else 0
        if policy is not None and policy.attempts > 0:
            for ev in prev.reschedule_tracker.events:
                if interval > 0 and now_ns - ev.reschedule_time_ns <= interval:
                    events.append(ev)
        else:
            events.extend(prev.reschedule_tracker.events[-MAX_PAST_RESCHEDULE_EVENTS:])
    next_delay = prev.next_delay_ns()
    events.append(
        RescheduleEvent(
            reschedule_time_ns=now_ns,
            prev_alloc_id=prev.id,
            prev_node_id=prev.node_id,
            delay_ns=next_delay,
        )
    )
    alloc.reschedule_tracker = RescheduleTracker(events=events)


def new_service_scheduler(logger, state, planner):
    return GenericScheduler(logger, state, planner, batch=False)


def new_batch_scheduler(logger, state, planner):
    return GenericScheduler(logger, state, planner, batch=True)
