"""Preemption: finding lower-priority allocations to evict.

Semantics follow reference ``scheduler/preemption.go`` — Preemptor :96,
PreemptForTaskGroup :198, PreemptForNetwork :270, PreemptForDevice :472,
distance metrics :608-660, filterAndGroupPreemptibleAllocs :663.
Greedy combinatorial search stays host-side; only distance scoring is a
candidate for vectorization.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs.funcs import remove_allocs
from ..structs.network import NetworkIndex
from ..structs.structs import (
    AllocatedResources,
    Allocation,
    ComparableResources,
    NetworkResource,
    Node,
    RequestedDevice,
)

# Penalty applied once more than max_parallel allocs of one job are preempted.
MAX_PARALLEL_PENALTY = 50.0

# Minimum priority delta for preemption eligibility.
PRIORITY_DELTA = 10


def preemption_enabled(sched_config, job_type: str) -> bool:
    """Whether the cluster's SchedulerConfiguration allows preemption for
    evals of ``job_type`` (reference structs/operator.go PreemptionConfig
    defaults: system on, service/batch off). The ONE switch both the host
    stack wiring (generic_sched.get_select_options, stack.SystemStack)
    and the device encode (tpu/engine) consult, so they can never
    disagree on whether an eval preempts."""
    from ..structs.structs import (
        JOB_TYPE_BATCH,
        JOB_TYPE_SYSTEM,
        PreemptionConfig,
    )

    pc = (
        sched_config.preemption_config
        if sched_config is not None
        else PreemptionConfig()
    )
    if job_type == JOB_TYPE_SYSTEM:
        return pc.system_scheduler_enabled
    if job_type == JOB_TYPE_BATCH:
        return pc.batch_scheduler_enabled
    return pc.service_scheduler_enabled


def basic_resource_distance(
    ask: ComparableResources, used: ComparableResources
) -> float:
    memory_coord = cpu_coord = disk_coord = 0.0
    if ask.flattened.memory_mb > 0:
        memory_coord = (ask.flattened.memory_mb - used.flattened.memory_mb) / float(
            ask.flattened.memory_mb
        )
    if ask.flattened.cpu_shares > 0:
        cpu_coord = (ask.flattened.cpu_shares - used.flattened.cpu_shares) / float(
            ask.flattened.cpu_shares
        )
    if ask.shared.disk_mb > 0:
        disk_coord = (ask.shared.disk_mb - used.shared.disk_mb) / float(ask.shared.disk_mb)
    return math.sqrt(memory_coord**2 + cpu_coord**2 + disk_coord**2)


def network_resource_distance(
    used: Optional[NetworkResource], needed: Optional[NetworkResource]
) -> float:
    if used is None or needed is None or needed.mbits == 0:
        return float("inf")
    return abs(float(needed.mbits - used.mbits) / float(needed.mbits))


def score_for_task_group(
    ask: ComparableResources,
    used: ComparableResources,
    max_parallel: int,
    num_preempted: int,
) -> float:
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def score_for_network(
    used: Optional[NetworkResource],
    needed: Optional[NetworkResource],
    max_parallel: int,
    num_preempted: int,
) -> float:
    if used is None or needed is None:
        return float("inf")
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return network_resource_distance(used, needed) + penalty


def filter_and_group_preemptible_allocs(
    job_priority: int, current: List[Allocation]
) -> List[Tuple[int, List[Allocation]]]:
    """Group by job priority ascending, dropping allocs within 10 points."""
    by_priority: Dict[int, List[Allocation]] = {}
    for alloc in current:
        if alloc.job is None:
            continue
        if job_priority - alloc.job.priority < PRIORITY_DELTA:
            continue
        by_priority.setdefault(alloc.job.priority, []).append(alloc)
    return sorted(by_priority.items(), key=lambda kv: kv[0])


def _distance_vec(ask: ComparableResources, used: np.ndarray) -> np.ndarray:
    """``basic_resource_distance`` over the candidate axis: ``used`` is an
    (n, 3) float64 tensor of [cpu, mem, disk]. Same IEEE-double ops in the
    same order as the scalar form, so results are bit-identical."""
    a_cpu = ask.flattened.cpu_shares
    a_mem = ask.flattened.memory_mb
    a_disk = ask.shared.disk_mb
    zero = np.zeros(used.shape[0])
    mem = (a_mem - used[:, 1]) / float(a_mem) if a_mem > 0 else zero
    cpu = (a_cpu - used[:, 0]) / float(a_cpu) if a_cpu > 0 else zero
    disk = (a_disk - used[:, 2]) / float(a_disk) if a_disk > 0 else zero
    return np.sqrt(mem * mem + cpu * cpu + disk * disk)


class _AllocInfo:
    __slots__ = ("max_parallel", "resources")

    def __init__(self, max_parallel: int, resources: ComparableResources):
        self.max_parallel = max_parallel
        self.resources = resources


class Preemptor:
    def __init__(self, job_priority: int, ctx, job_namespaced_id) -> None:
        self.current_preemptions: Dict[Tuple[str, str], Dict[str, int]] = {}
        self.alloc_details: Dict[str, _AllocInfo] = {}
        self.job_priority = job_priority
        self.job_id = job_namespaced_id  # (namespace, id) tuple or None
        self.node_remaining_resources: Optional[ComparableResources] = None
        self.current_allocs: List[Allocation] = []
        self.ctx = ctx

    def set_node(self, node: Node) -> None:
        remaining = node.comparable_resources()
        reserved = node.comparable_reserved_resources()
        if reserved is not None:
            remaining.subtract(reserved)
        self.node_remaining_resources = remaining

    def set_candidates(self, allocs: List[Allocation]) -> None:
        self.current_allocs = []
        for alloc in allocs:
            if self.job_id is not None and (alloc.namespace, alloc.job_id) == (
                self.job_id[0],
                self.job_id[1],
            ):
                continue
            max_parallel = 0
            if alloc.job is not None:
                tg = alloc.job.lookup_task_group(alloc.task_group)
                if tg is not None and tg.migrate is not None:
                    max_parallel = tg.migrate.max_parallel
            self.alloc_details[alloc.id] = _AllocInfo(max_parallel, alloc.comparable_resources())
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: List[Allocation]) -> None:
        self.current_preemptions = {}
        for alloc in allocs:
            key = (alloc.job_id, alloc.namespace)
            self.current_preemptions.setdefault(key, {})
            self.current_preemptions[key][alloc.task_group] = (
                self.current_preemptions[key].get(alloc.task_group, 0) + 1
            )

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get((alloc.job_id, alloc.namespace), {}).get(
            alloc.task_group, 0
        )

    def _group_score_arrays(self, grp: List[Allocation]):
        """Static per-candidate score inputs: (n, 3) used-resource tensor
        + max_parallel penalty vector (both constant across greedy rounds)."""
        n = len(grp)
        used = np.empty((n, 3), np.float64)
        penalty = np.empty(n, np.float64)
        for i, alloc in enumerate(grp):
            details = self.alloc_details[alloc.id]
            r = details.resources
            used[i, 0] = r.flattened.cpu_shares
            used[i, 1] = r.flattened.memory_mb
            used[i, 2] = r.shared.disk_mb
            num = self._num_preemptions(alloc)
            mp = details.max_parallel
            penalty[i] = (
                float((num + 1) - mp) * MAX_PARALLEL_PENALTY
                if (mp > 0 and num >= mp)
                else 0.0
            )
        return used, penalty

    # -- task group (cpu/mem/disk) ----------------------------------------

    def preempt_for_task_group(self, resource_ask: AllocatedResources) -> List[Allocation]:
        resources_needed = resource_ask.comparable()

        for alloc in self.current_allocs:
            self.node_remaining_resources.subtract(self.alloc_details[alloc.id].resources)

        # Deterministic (parity) mode: the exact integer spec of
        # tpu/preempt.py IS the selection algorithm, shared verbatim with
        # the device kernel so host and device eviction sets are
        # bit-identical on every backend. Float64 remains the
        # throughput-mode scorer below.
        if self.ctx is not None and getattr(self.ctx, "deterministic", False):
            return self._preempt_for_task_group_int(resource_ask)

        allocs_by_priority = filter_and_group_preemptible_allocs(
            self.job_priority, self.current_allocs
        )

        best_allocs: List[Allocation] = []
        all_requirements_met = False
        available = self.node_remaining_resources.copy()
        resources_asked = resource_ask.comparable()

        for _priority, grp_allocs in allocs_by_priority:
            grp = list(grp_allocs)
            # Distance scoring is tensor math over the candidate axis:
            # the used-resource coordinates and the max_parallel penalty
            # are static across greedy rounds (set_preemptions is not
            # updated mid-search), so they encode once per group; each
            # round recomputes the distance vector against the shrinking
            # ask in one vectorized op. np.argmin's first-occurrence rule
            # matches the scalar loop's strict < scan, so selections are
            # bit-identical (same IEEE-double ops either way).
            used, penalty = self._group_score_arrays(grp)
            alive = np.ones(len(grp), bool)
            while alive.any() and not all_requirements_met:
                dist = _distance_vec(resources_needed, used) + penalty
                dist = np.where(alive, dist, np.inf)
                closest_index = int(np.argmin(dist))
                alive[closest_index] = False
                closest = grp[closest_index]
                closest_resources = self.alloc_details[closest.id].resources
                available.add(closest_resources)
                all_requirements_met, _ = available.superset(resources_asked)
                best_allocs.append(closest)
                resources_needed.subtract(closest_resources)
            if all_requirements_met:
                break

        if not all_requirements_met:
            return []

        # Second pass: drop allocs whose resources are already covered.
        resources_needed = resource_ask.comparable()
        return self._filter_superset_basic(
            best_allocs, self.node_remaining_resources, resources_needed
        )

    def _preempt_for_task_group_int(self, resource_ask: AllocatedResources) -> List[Allocation]:
        """Integer-spec selection (deterministic mode): flatten the
        candidate list in insertion order and run the shared greedy +
        second-pass spec. ``node_remaining_resources`` has already had
        every candidate subtracted by the caller."""
        from ..tpu.preempt import penalty_q_py, select_eviction_set_py

        ask_cmp = resource_ask.comparable()
        ask3 = [
            int(ask_cmp.flattened.cpu_shares),
            int(ask_cmp.flattened.memory_mb),
            int(ask_cmp.shared.disk_mb),
        ]
        rem = self.node_remaining_resources
        remaining3 = [
            int(rem.flattened.cpu_shares),
            int(rem.flattened.memory_mb),
            int(rem.shared.disk_mb),
        ]
        res3: List[List[int]] = []
        prio: List[int] = []
        pen: List[int] = []
        elig: List[bool] = []
        for alloc in self.current_allocs:
            details = self.alloc_details[alloc.id]
            r = details.resources
            res3.append([
                int(r.flattened.cpu_shares),
                int(r.flattened.memory_mb),
                int(r.shared.disk_mb),
            ])
            ok = (
                alloc.job is not None
                and self.job_priority - alloc.job.priority >= PRIORITY_DELTA
            )
            elig.append(ok)
            prio.append(alloc.job.priority if alloc.job is not None else 0)
            pen.append(penalty_q_py(details.max_parallel, self._num_preemptions(alloc)))
        sel = select_eviction_set_py(ask3, remaining3, res3, prio, pen, elig)
        if sel is None:
            return []
        return [self.current_allocs[i] for i in sel]

    def _filter_superset_basic(
        self,
        best_allocs: List[Allocation],
        node_remaining: ComparableResources,
        ask: ComparableResources,
    ) -> List[Allocation]:
        used, _ = self._group_score_arrays(best_allocs)
        dist = _distance_vec(ask, used)
        best_allocs = [
            best_allocs[i]
            for i in sorted(range(len(best_allocs)), key=dist.__getitem__, reverse=True)
        ]
        available = node_remaining.copy()
        filtered: List[Allocation] = []
        for alloc in best_allocs:
            filtered.append(alloc)
            available.add(self.alloc_details[alloc.id].resources)
            met, _ = available.superset(ask)
            if met:
                break
        return filtered

    # -- network -----------------------------------------------------------

    def preempt_for_network(
        self, ask: NetworkResource, net_idx: NetworkIndex
    ) -> Optional[List[Allocation]]:
        if not self.current_allocs:
            return None

        mbits_needed = ask.mbits
        reserved_ports_needed = ask.reserved_ports
        filtered_reserved_ports: Dict[str, set] = {}
        device_to_allocs: Dict[str, List[Allocation]] = {}

        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            networks = self._first_network_list(alloc)
            if not networks:
                continue
            net = networks[0]
            if self.job_priority - alloc.job.priority < PRIORITY_DELTA:
                for port in net.reserved_ports:
                    filtered_reserved_ports.setdefault(net.device, set()).add(port.value)
                continue
            device_to_allocs.setdefault(net.device, []).append(alloc)

        if not device_to_allocs:
            return None

        allocs_to_preempt: List[Allocation] = []
        met = False
        free_bandwidth = 0
        preempted_device = ""

        for device, current_allocs in device_to_allocs.items():
            preempted_device = device
            total_bandwidth = net_idx.avail_bandwidth.get(device, 0)
            if total_bandwidth < mbits_needed:
                continue
            free_bandwidth = total_bandwidth - net_idx.used_bandwidth.get(device, 0)
            preempted_bandwidth = 0
            allocs_to_preempt = []

            if reserved_ports_needed:
                used_port_to_alloc: Dict[int, Allocation] = {}
                for alloc in current_allocs:
                    for n in self._first_network_list(alloc):
                        for p in n.reserved_ports:
                            used_port_to_alloc[p.value] = alloc
                skip_device = False
                for port in reserved_ports_needed:
                    alloc = used_port_to_alloc.get(port.value)
                    if alloc is not None:
                        preempted_bandwidth += self._first_network_list(alloc)[0].mbits
                        allocs_to_preempt.append(alloc)
                    elif port.value in filtered_reserved_ports.get(device, set()):
                        skip_device = True
                        break
                if skip_device:
                    continue
                current_allocs = remove_allocs(current_allocs, allocs_to_preempt)

            if preempted_bandwidth + free_bandwidth >= mbits_needed:
                met = True
                break

            for _priority, grp in filter_and_group_preemptible_allocs(
                self.job_priority, current_allocs
            ):
                grp = sorted(grp, key=lambda a: self._network_distance_key(a, ask))
                done = False
                for alloc in grp:
                    preempted_bandwidth += self._first_network_list(alloc)[0].mbits
                    allocs_to_preempt.append(alloc)
                    if preempted_bandwidth + free_bandwidth >= mbits_needed:
                        met = True
                        done = True
                        break
                if done:
                    break
            if met:
                break

        if not met:
            return None

        # Final superset pass on network distance.
        def net_used(a: Allocation) -> Optional[NetworkResource]:
            nets = self._first_network_list(a)
            return nets[0] if nets else None

        allocs_sorted = sorted(
            allocs_to_preempt,
            key=lambda a: network_resource_distance(net_used(a), ask),
            reverse=True,
        )
        available_mbits = free_bandwidth
        filtered: List[Allocation] = []
        for alloc in allocs_sorted:
            filtered.append(alloc)
            used = net_used(alloc)
            if used is not None:
                available_mbits += used.mbits
            if available_mbits > 0 and mbits_needed > 0 and available_mbits >= mbits_needed:
                break
        return filtered

    def _network_distance_key(self, alloc: Allocation, ask: NetworkResource) -> float:
        details = self.alloc_details[alloc.id]
        nets = details.resources.flattened.networks
        used = nets[0] if nets else None
        max_parallel = details.max_parallel
        return score_for_network(used, ask, max_parallel, self._num_preemptions(alloc))

    def _first_network_list(self, alloc: Allocation) -> List[NetworkResource]:
        details = self.alloc_details.get(alloc.id)
        if details is not None:
            return details.resources.flattened.networks
        return alloc.comparable_resources().flattened.networks

    # -- devices -----------------------------------------------------------

    def preempt_for_device(self, ask: RequestedDevice, dev_alloc) -> Optional[List[Allocation]]:
        from .feasible import node_device_matches

        device_to_allocs: Dict[object, Tuple[List[Allocation], Dict[str, int]]] = {}
        for alloc in self.current_allocs:
            if alloc.allocated_resources is None:
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for device in tr.devices:
                    dev_id = device.id()
                    dev_inst = dev_alloc.devices.get(dev_id)
                    if dev_inst is None:
                        continue
                    if not node_device_matches(self.ctx, dev_inst.device, ask):
                        continue
                    allocs, instances = device_to_allocs.setdefault(dev_id, ([], {}))
                    allocs.append(alloc)
                    instances[alloc.id] = instances.get(alloc.id, 0) + len(device.device_ids)

        needed_count = ask.count
        preemption_options: List[Tuple[List[Allocation], Dict[str, int]]] = []

        for dev_id, (allocs, instances) in device_to_allocs.items():
            preempted_count = 0
            preempted_allocs: List[Allocation] = []
            satisfied = False
            for _priority, grp in filter_and_group_preemptible_allocs(self.job_priority, allocs):
                for alloc in grp:
                    dev_inst = dev_alloc.devices[dev_id]
                    preempted_count += instances[alloc.id]
                    preempted_allocs.append(alloc)
                    if preempted_count + dev_inst.free_count() >= needed_count:
                        preemption_options.append((preempted_allocs, instances))
                        satisfied = True
                        break
                if satisfied:
                    break

        if preemption_options:
            return _select_best_allocs(preemption_options, needed_count)
        return None


def _select_best_allocs(
    preemption_options: List[Tuple[List[Allocation], Dict[str, int]]], needed_count: int
) -> List[Allocation]:
    """Pick the option with the lowest net (sum of unique) priority."""
    best_priority = float("inf")
    best_allocs: List[Allocation] = []
    for allocs, instances in preemption_options:
        priorities = set()
        net_priority = 0
        filtered: List[Allocation] = []
        allocs = sorted(allocs, key=lambda a: instances[a.id], reverse=True)
        preempted_instance_count = 0
        for alloc in allocs:
            if preempted_instance_count >= needed_count:
                break
            preempted_instance_count += instances[alloc.id]
            filtered.append(alloc)
            if alloc.job is not None and alloc.job.priority not in priorities:
                priorities.add(alloc.job.priority)
                net_priority += alloc.job.priority
        if net_priority < best_priority:
            best_priority = net_priority
            best_allocs = filtered
    return best_allocs
