"""Property-value usage tracking for distinct_property and spread.

Semantics follow reference ``scheduler/propertyset.go``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..structs.structs import Allocation, Job, Node
from .context import EvalContext


def get_property(node: Optional[Node], prop: str) -> Tuple[str, bool]:
    from .feasible import resolve_target

    if node is None or not prop:
        return "", False
    val, ok = resolve_target(prop, node)
    if not ok or not isinstance(val, str):
        return "", False
    return val, True


class PropertySet:
    def __init__(self, ctx: EvalContext, job: Optional[Job]) -> None:
        self.ctx = ctx
        self.job_id = job.id if job else ""
        self.namespace = job.namespace if job else "default"
        self.task_group = ""
        self.target_attribute = ""
        self.allowed_count = 0
        self.error_building: Optional[str] = None
        self.existing_values: Dict[str, int] = {}
        self.proposed_values: Dict[str, int] = {}
        self.cleared_values: Dict[str, int] = {}

    # -- configuration -----------------------------------------------------

    def set_job_constraint(self, constraint) -> None:
        self._set_constraint(constraint, "")

    def set_tg_constraint(self, constraint, task_group: str) -> None:
        self._set_constraint(constraint, task_group)

    def _set_constraint(self, constraint, task_group: str) -> None:
        if constraint.rtarget:
            try:
                allowed = int(constraint.rtarget)
            except ValueError:
                self.error_building = (
                    f"failed to convert RTarget {constraint.rtarget!r} to uint64"
                )
                return
        else:
            allowed = 1
        self._set_target_attribute_with_count(constraint.ltarget, allowed, task_group)

    def set_target_attribute(self, target_attribute: str, task_group: str) -> None:
        self._set_target_attribute_with_count(target_attribute, 0, task_group)

    def _set_target_attribute_with_count(
        self, target_attribute: str, allowed_count: int, task_group: str
    ) -> None:
        if task_group:
            self.task_group = task_group
        self.target_attribute = target_attribute
        self.allowed_count = allowed_count
        self._populate_existing()
        self.populate_proposed()

    # -- population --------------------------------------------------------

    def _populate_existing(self) -> None:
        allocs = self.ctx.state.allocs_by_job(self.namespace, self.job_id, False)
        allocs = self._filter_allocs(allocs, filter_terminal=True)
        nodes = self._build_node_map(allocs)
        self._populate_properties(allocs, nodes, self.existing_values)

    def populate_proposed(self) -> None:
        self.proposed_values = {}
        self.cleared_values = {}

        stopping: List[Allocation] = []
        for updates in self.ctx.plan.node_update.values():
            stopping.extend(updates)
        stopping = self._filter_allocs(stopping, filter_terminal=False)

        proposed: List[Allocation] = []
        for pallocs in self.ctx.plan.node_allocation.values():
            proposed.extend(pallocs)
        proposed = self._filter_allocs(proposed, filter_terminal=True)

        nodes = self._build_node_map(stopping + proposed)
        self._populate_properties(stopping, nodes, self.cleared_values)
        self._populate_properties(proposed, nodes, self.proposed_values)

        # A cleared value now re-used by a proposed alloc isn't really cleared.
        for value in list(self.proposed_values):
            current = self.cleared_values.get(value)
            if current is None:
                continue
            if current == 0:
                del self.cleared_values[value]
            elif current > 1:
                self.cleared_values[value] -= 1

    # -- queries -----------------------------------------------------------

    def satisfies_distinct_properties(self, option: Node, tg: str) -> Tuple[bool, str]:
        nvalue, error_msg, used_count = self.used_count(option, tg)
        if error_msg:
            return False, error_msg
        if used_count < self.allowed_count:
            return True, ""
        return False, (
            f"distinct_property: {self.target_attribute}={nvalue} used by {used_count} allocs"
        )

    def used_count(self, option: Node, tg: str) -> Tuple[str, str, int]:
        if self.error_building is not None:
            return "", self.error_building, 0
        nvalue, ok = get_property(option, self.target_attribute)
        if not ok:
            return nvalue, f'missing property "{self.target_attribute}"', 0
        combined = self.get_combined_use_map()
        return nvalue, "", combined.get(nvalue, 0)

    def get_combined_use_map(self) -> Dict[str, int]:
        combined: Dict[str, int] = {}
        for used_values in (self.existing_values, self.proposed_values):
            for value, count in used_values.items():
                combined[value] = combined.get(value, 0) + count
        for value, cleared in self.cleared_values.items():
            if value not in combined:
                continue
            combined[value] = max(combined[value] - cleared, 0)
        return combined

    # -- helpers -----------------------------------------------------------

    def _filter_allocs(self, allocs: List[Allocation], filter_terminal: bool) -> List[Allocation]:
        out = []
        for a in allocs:
            if filter_terminal and a.terminal_status():
                continue
            if self.task_group and a.task_group != self.task_group:
                continue
            out.append(a)
        return out

    def _build_node_map(self, allocs: List[Allocation]) -> Dict[str, Optional[Node]]:
        nodes: Dict[str, Optional[Node]] = {}
        for alloc in allocs:
            if alloc.node_id in nodes:
                continue
            nodes[alloc.node_id] = self.ctx.state.node_by_id(alloc.node_id)
        return nodes

    def _populate_properties(
        self,
        allocs: List[Allocation],
        nodes: Dict[str, Optional[Node]],
        properties: Dict[str, int],
    ) -> None:
        for alloc in allocs:
            nprop, ok = get_property(nodes.get(alloc.node_id), self.target_attribute)
            if not ok:
                continue
            properties[nprop] = properties.get(nprop, 0) + 1
