"""Host-side ranking iterators.

Semantics follow reference ``scheduler/rank.go`` — BinPackIterator :146,
JobAntiAffinityIterator :456, NodeReschedulingPenaltyIterator :526,
NodeAffinityIterator :571, ScoreNormalizationIterator :661. Each scoring
term here corresponds to an additive score tensor in the TPU engine.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..structs.funcs import BIN_PACKING_MAX_FIT_SCORE, allocs_fit, remove_allocs, score_fit
from ..structs.network import NetworkIndex
from ..structs.structs import (
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    Job,
    Node,
    TaskGroup,
)
from .context import EvalContext
from .device import DeviceAllocator


class RankedNode:
    def __init__(self, node: Node) -> None:
        self.node = node
        self.final_score = 0.0
        self.scores: List[float] = []
        self.task_resources: Dict[str, AllocatedTaskResources] = {}
        self.alloc_resources: Optional[AllocatedSharedResources] = None
        self.proposed: Optional[List[Allocation]] = None
        self.preempted_allocs: Optional[List[Allocation]] = None

    def proposed_allocs(self, ctx: EvalContext) -> List[Allocation]:
        if self.proposed is None:
            self.proposed = ctx.proposed_allocs(self.node.id)
        return self.proposed

    def set_task_resources(self, task, resource: AllocatedTaskResources) -> None:
        self.task_resources[task.name] = resource

    def __repr__(self) -> str:
        return f"<Node: {self.node.id} Score: {self.final_score:.3f}>"


class _FitProbe:
    """Duck-typed stand-in for the would-be placement in the final fit
    check: allocs_fit only calls terminal_status() and
    comparable_resources(), so minting a UUID-bearing Allocation per
    node visit is pure id-generation overhead at ranking volume."""

    __slots__ = ("_resources",)

    def __init__(self, resources: AllocatedResources) -> None:
        self._resources = resources

    @staticmethod
    def terminal_status() -> bool:
        return False

    def comparable_resources(self):
        return self._resources.comparable()


class FeasibleRankIterator:
    """Upgrades a feasible iterator to a rank iterator."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        return RankedNode(option)

    def reset(self) -> None:
        self.source.reset()


class StaticRankIterator:
    """A fixed list of ranked nodes (testing only)."""

    def __init__(self, ctx: EvalContext, nodes: List[RankedNode]) -> None:
        self.ctx = ctx
        self.nodes = nodes
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[RankedNode]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        return option

    def reset(self) -> None:
        self.seen = 0


class BinPackIterator:
    """Fits the task group onto each candidate, scoring with BestFit-v3.

    Handles per-task cpu/mem, group+task network asks, device assignment, and
    (when ``evict`` is set) preemption (reference rank.go:176).
    """

    def __init__(self, ctx: EvalContext, source, evict: bool, priority: int) -> None:
        self.ctx = ctx
        self.source = source
        self.evict = evict
        self.priority = priority
        self.job_namespaced_id = None
        self.task_group: Optional[TaskGroup] = None

    def set_job(self, job: Job) -> None:
        self.priority = job.priority
        self.job_namespaced_id = job.namespaced_id()

    def set_task_group(self, task_group: TaskGroup) -> None:
        self.task_group = task_group

    def next(self) -> Optional[RankedNode]:
        from ..utils import phases as _phases

        # "rank" attributes the whole host placement pull: the upstream
        # feasibility iterator chain executes inside self.source.next(),
        # so one span here covers feasibility + network/device fit +
        # scoring for this candidate (the region round 5 left untracked)
        with _phases.track("rank"):
            return self._next_ranked()

    def _next_ranked(self) -> Optional[RankedNode]:
        from .preemption import Preemptor

        while True:
            option = self.source.next()
            if option is None:
                return None

            proposed = option.proposed_allocs(self.ctx)

            # forked from the ctx's per-node cached base index; our
            # add_reserved calls stay private to this candidate visit
            net_idx = self.ctx.network_index(option.node, proposed)

            dev_allocator = DeviceAllocator(self.ctx, option.node)
            dev_allocator.add_allocs(proposed)

            total_device_affinity_weight = 0.0
            sum_matching_affinities = 0.0

            total = AllocatedResources(
                shared=AllocatedSharedResources(
                    disk_mb=self.task_group.ephemeral_disk.size_mb
                )
            )

            allocs_to_preempt: List[Allocation] = []
            preemptor = Preemptor(self.priority, self.ctx, self.job_namespaced_id)
            preemptor.set_node(option.node)
            current_preemptions = [
                a for allocs in self.ctx.plan.node_preemptions.values() for a in allocs
            ]
            preemptor.set_preemptions(current_preemptions)

            exhausted = False

            # Task-group-level network ask
            if self.task_group.networks:
                ask = self.task_group.networks[0].copy()
                offer, err = net_idx.assign_network(ask)
                if offer is None:
                    if not self.evict:
                        self.ctx.metrics.exhausted_node(option.node, f"network: {err}")
                        continue
                    preemptor.set_candidates(proposed)
                    net_preemptions = preemptor.preempt_for_network(ask, net_idx)
                    if net_preemptions is None:
                        continue
                    allocs_to_preempt.extend(net_preemptions)
                    proposed = remove_allocs(proposed, net_preemptions)
                    net_idx = NetworkIndex(deterministic=self.ctx.deterministic)
                    net_idx.set_node(option.node)
                    net_idx.add_allocs(proposed)
                    offer, err = net_idx.assign_network(ask)
                    if offer is None:
                        continue
                net_idx.add_reserved(offer)
                total.shared.networks = [offer]
                option.alloc_resources = AllocatedSharedResources(
                    networks=[offer], disk_mb=self.task_group.ephemeral_disk.size_mb
                )

            for task in self.task_group.tasks:
                task_resources = AllocatedTaskResources(
                    cpu_shares=task.resources.cpu, memory_mb=task.resources.memory_mb
                )

                if task.resources.networks:
                    ask = task.resources.networks[0].copy()
                    offer, err = net_idx.assign_network(ask)
                    if offer is None:
                        if not self.evict:
                            self.ctx.metrics.exhausted_node(option.node, f"network: {err}")
                            exhausted = True
                            break
                        preemptor.set_candidates(proposed)
                        net_preemptions = preemptor.preempt_for_network(ask, net_idx)
                        if net_preemptions is None:
                            exhausted = True
                            break
                        allocs_to_preempt.extend(net_preemptions)
                        proposed = remove_allocs(proposed, net_preemptions)
                        net_idx = NetworkIndex(deterministic=self.ctx.deterministic)
                        net_idx.set_node(option.node)
                        net_idx.add_allocs(proposed)
                        offer, err = net_idx.assign_network(ask)
                        if offer is None:
                            exhausted = True
                            break
                    net_idx.add_reserved(offer)
                    task_resources.networks = [offer]

                for req in task.resources.devices:
                    offer, sum_affinities, err = dev_allocator.assign_device(req)
                    if offer is None:
                        if not self.evict:
                            self.ctx.metrics.exhausted_node(option.node, f"devices: {err}")
                            exhausted = True
                            break
                        preemptor.set_candidates(proposed)
                        device_preemptions = preemptor.preempt_for_device(req, dev_allocator)
                        if device_preemptions is None:
                            exhausted = True
                            break
                        allocs_to_preempt.extend(device_preemptions)
                        proposed = remove_allocs(proposed, allocs_to_preempt)
                        dev_allocator = DeviceAllocator(self.ctx, option.node)
                        dev_allocator.add_allocs(proposed)
                        offer, sum_affinities, err = dev_allocator.assign_device(req)
                        if offer is None:
                            exhausted = True
                            break
                    dev_allocator.add_reserved(offer)
                    task_resources.devices.append(offer)
                    if req.affinities:
                        for a in req.affinities:
                            total_device_affinity_weight += abs(float(a.weight))
                        sum_matching_affinities += sum_affinities
                if exhausted:
                    break

                option.set_task_resources(task, task_resources)
                total.tasks[task.name] = task_resources

            if exhausted:
                continue

            current = proposed
            proposed = proposed + [_FitProbe(total)]

            fit, dim, used = allocs_fit(option.node, proposed, net_idx, check_devices=False)
            if not fit:
                if not self.evict:
                    self.ctx.metrics.exhausted_node(option.node, dim)
                    continue
                preemptor.set_candidates(current)
                preempted_allocs = preemptor.preempt_for_task_group(total)
                allocs_to_preempt.extend(preempted_allocs)
                if not preempted_allocs:
                    self.ctx.metrics.exhausted_node(option.node, dim)
                    continue
            if allocs_to_preempt:
                option.preempted_allocs = allocs_to_preempt

            fitness = score_fit(option.node, used)
            normalized_fit = fitness / BIN_PACKING_MAX_FIT_SCORE
            option.scores.append(normalized_fit)
            self.ctx.metrics.score_node(option.node, "binpack", normalized_fit)

            if total_device_affinity_weight != 0:
                sum_matching_affinities /= total_device_affinity_weight
                option.scores.append(sum_matching_affinities)
                self.ctx.metrics.score_node(option.node, "devices", sum_matching_affinities)

            return option

    def reset(self) -> None:
        self.source.reset()


class JobAntiAffinityIterator:
    """Penalizes co-placement with allocs of the same job+group."""

    def __init__(self, ctx: EvalContext, source, job_id: str) -> None:
        self.ctx = ctx
        self.source = source
        self.job_id = job_id
        self.task_group = ""
        self.desired_count = 0

    def set_job(self, job: Job) -> None:
        self.job_id = job.id

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg.name
        self.desired_count = tg.count

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None:
                return None
            proposed = option.proposed_allocs(self.ctx)
            collisions = sum(
                1
                for alloc in proposed
                if alloc.job_id == self.job_id and alloc.task_group == self.task_group
            )
            if collisions > 0:
                score_penalty = -1.0 * float(collisions + 1) / float(self.desired_count)
                option.scores.append(score_penalty)
                self.ctx.metrics.score_node(option.node, "job-anti-affinity", score_penalty)
            else:
                self.ctx.metrics.score_node(option.node, "job-anti-affinity", 0)
            return option

    def reset(self) -> None:
        self.source.reset()


class NodeReschedulingPenaltyIterator:
    """Penalizes nodes where this alloc previously failed."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.penalty_nodes: Set[str] = set()

    def set_penalty_nodes(self, penalty_nodes: Set[str]) -> None:
        self.penalty_nodes = penalty_nodes or set()

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if option.node.id in self.penalty_nodes:
            option.scores.append(-1.0)
            self.ctx.metrics.score_node(option.node, "node-reschedule-penalty", -1)
        else:
            self.ctx.metrics.score_node(option.node, "node-reschedule-penalty", 0)
        return option

    def reset(self) -> None:
        self.penalty_nodes = set()
        self.source.reset()


class NodeAffinityIterator:
    """Weighted affinity scoring over job+group+task affinity stanzas."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.job_affinities = []
        self.affinities = []

    def set_job(self, job: Job) -> None:
        self.job_affinities = list(job.affinities)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.affinities = list(self.job_affinities)
        self.affinities.extend(tg.affinities)
        for task in tg.tasks:
            self.affinities.extend(task.affinities)

    def reset(self) -> None:
        self.source.reset()
        self.affinities = []

    def has_affinities(self) -> bool:
        return bool(self.affinities)

    def next(self) -> Optional[RankedNode]:
        from .feasible import matches_affinity

        option = self.source.next()
        if option is None:
            return None
        if not self.has_affinities():
            self.ctx.metrics.score_node(option.node, "node-affinity", 0)
            return option
        sum_weight = sum(abs(float(a.weight)) for a in self.affinities)
        total = 0.0
        for affinity in self.affinities:
            if matches_affinity(self.ctx, affinity, option.node):
                total += float(affinity.weight)
        # total != 0 implies sum_weight != 0; all-zero weights are a no-op.
        if total != 0.0:
            norm_score = total / sum_weight
            option.scores.append(norm_score)
            self.ctx.metrics.score_node(option.node, "node-affinity", norm_score)
        return option


class ScoreNormalizationIterator:
    """Final score = mean of accumulated score terms (reference rank.go:678)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not option.scores:
            return option
        option.final_score = sum(option.scores) / len(option.scores)
        self.ctx.metrics.score_node(option.node, "normalized-score", option.final_score)
        return option
