"""The allocation reconciler: desired-vs-actual diff per task group.

Semantics follow reference ``scheduler/reconcile.go`` (allocReconciler :39,
Compute :184, computeGroup :306, computeLimit :618, computePlacements :662,
computeStop :699, computeUpdates :810, handleDelayedReschedules :833). Pure
host-side logic — no device work.
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..structs.structs import (
    ALLOC_CLIENT_LOST,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    Allocation,
    Deployment,
    DeploymentState,
    DeploymentStatusUpdate,
    DesiredUpdates,
    Evaluation,
    Job,
    Node,
    TaskGroup,
)
from .reconcile_util import (
    AllocDestructiveResult,
    AllocNameIndex,
    AllocPlaceResult,
    AllocSet,
    AllocStopResult,
    DelayedRescheduleInfo,
    alloc_index,
    filter_by_terminal,
    new_alloc_matrix,
)
from .util import (
    ALLOC_LOST,
    ALLOC_MIGRATING,
    ALLOC_NOT_NEEDED,
    ALLOC_RESCHEDULED,
    ALLOC_UPDATING,
    RESCHEDULING_FOLLOWUP_EVAL_DESC,
)

BATCHED_FAILED_ALLOC_WINDOW_NS = 5 * 10**9  # batch follow-up evals within 5s

# allocUpdateFn: (existing, new_job, new_tg) -> (ignore, destructive, updated)
AllocUpdateFn = Callable[
    [Allocation, Job, TaskGroup], Tuple[bool, bool, Optional[Allocation]]
]


@dataclass
class ReconcileResults:
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    place: List[AllocPlaceResult] = field(default_factory=list)
    destructive_update: List[AllocDestructiveResult] = field(default_factory=list)
    inplace_update: List[Allocation] = field(default_factory=list)
    stop: List[AllocStopResult] = field(default_factory=list)
    attribute_updates: Dict[str, Allocation] = field(default_factory=dict)
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    desired_followup_evals: Dict[str, List[Evaluation]] = field(default_factory=dict)

    def changes(self) -> int:
        return len(self.place) + len(self.inplace_update) + len(self.stop)


def _update_is_empty(u) -> bool:
    return u is None or u.max_parallel == 0


def new_deployment(job: Job) -> Deployment:
    return Deployment(
        namespace=job.namespace,
        job_id=job.id,
        job_version=job.version,
        job_modify_index=job.job_modify_index,
        job_create_index=job.create_index,
        status="running",
        status_description="Deployment is running",
    )


class AllocReconciler:
    def __init__(
        self,
        logger,
        alloc_update_fn: AllocUpdateFn,
        batch: bool,
        job_id: str,
        job: Optional[Job],
        deployment: Optional[Deployment],
        existing_allocs: List[Allocation],
        tainted_nodes: Dict[str, Optional[Node]],
        eval_id: str,
        now_ns: Optional[int] = None,
    ) -> None:
        self.logger = logger
        self.alloc_update_fn = alloc_update_fn
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.old_deployment: Optional[Deployment] = None
        self.deployment = deployment.copy() if deployment is not None else None
        self.deployment_paused = False
        self.deployment_failed = False
        self.tainted_nodes = tainted_nodes
        self.existing_allocs = existing_allocs
        self.eval_id = eval_id
        self.now_ns = now_ns if now_ns is not None else _time.time_ns()
        self.result = ReconcileResults()

    # ------------------------------------------------------------------

    def compute(self) -> ReconcileResults:
        m = new_alloc_matrix(self.job, self.existing_allocs)

        self._cancel_deployments()

        if self.job is None or self.job.stopped():
            self._handle_stop(m)
            return self.result

        if self.deployment is not None:
            self.deployment_paused = self.deployment.status == DEPLOYMENT_STATUS_PAUSED
            self.deployment_failed = self.deployment.status == DEPLOYMENT_STATUS_FAILED

        complete = True
        for group, allocs in m.items():
            group_complete = self._compute_group(group, allocs)
            complete = complete and group_complete

        if self.deployment is not None and complete:
            self.result.deployment_updates.append(
                DeploymentStatusUpdate(
                    deployment_id=self.deployment.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description="Deployment completed successfully",
                )
            )

        d = self.result.deployment
        if d is not None and d.requires_promotion():
            # Auto-promotion only happens when EVERY group opts in
            # (reference Deployment.HasAutoPromote).
            auto = all(s.auto_promote for s in d.task_groups.values())
            d.status_description = (
                "Deployment is running pending automatic promotion"
                if auto
                else "Deployment is running but requires manual promotion"
            )

        return self.result

    # ------------------------------------------------------------------

    def _cancel_deployments(self) -> None:
        if self.job is None or self.job.stopped():
            if self.deployment is not None and self.deployment.active():
                self.result.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=self.deployment.id,
                        status=DEPLOYMENT_STATUS_CANCELLED,
                        status_description="Cancelled because job is stopped",
                    )
                )
            self.old_deployment = self.deployment
            self.deployment = None
            return

        d = self.deployment
        if d is None:
            return

        if d.job_create_index != self.job.create_index or d.job_version != self.job.version:
            if d.active():
                self.result.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=d.id,
                        status=DEPLOYMENT_STATUS_CANCELLED,
                        status_description="Cancelled due to newer version of job",
                    )
                )
            self.old_deployment = d
            self.deployment = None

        elif d.status == DEPLOYMENT_STATUS_SUCCESSFUL:
            self.old_deployment = d
            self.deployment = None

    def _handle_stop(self, m: Dict[str, AllocSet]) -> None:
        for group, allocs in m.items():
            allocs = filter_by_terminal(allocs)
            untainted, migrate, lost = allocs.filter_by_tainted(self.tainted_nodes)
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            desired = DesiredUpdates()
            desired.stop = len(allocs)
            self.result.desired_tg_updates[group] = desired

    def _mark_stop(self, allocs: AllocSet, client_status: str, status_description: str) -> None:
        for alloc in allocs.values():
            self.result.stop.append(
                AllocStopResult(
                    alloc=alloc,
                    client_status=client_status,
                    status_description=status_description,
                )
            )

    # ------------------------------------------------------------------

    def _compute_group(self, group: str, all_allocs: AllocSet) -> bool:
        desired_changes = DesiredUpdates()
        self.result.desired_tg_updates[group] = desired_changes

        tg = self.job.lookup_task_group(group)
        if tg is None:
            untainted, migrate, lost = all_allocs.filter_by_tainted(self.tainted_nodes)
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            desired_changes.stop = len(untainted) + len(migrate) + len(lost)
            return True

        dstate: Optional[DeploymentState] = None
        existing_deployment = False
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(group)
            existing_deployment = dstate is not None
        if not existing_deployment:
            dstate = DeploymentState()
            if not _update_is_empty(tg.update):
                dstate.auto_revert = tg.update.auto_revert
                dstate.auto_promote = tg.update.auto_promote
                dstate.progress_deadline_ns = tg.update.progress_deadline_ns

        all_allocs, ignore = self._filter_old_terminal_allocs(all_allocs)
        desired_changes.ignore += len(ignore)

        canaries, all_allocs = self._handle_group_canaries(all_allocs, desired_changes)

        untainted, migrate, lost = all_allocs.filter_by_tainted(self.tainted_nodes)

        untainted, reschedule_now, reschedule_later = untainted.filter_by_rescheduleable(
            self.batch, self.now_ns, self.eval_id, self.deployment
        )

        self._handle_delayed_reschedules(reschedule_later, all_allocs, tg.name)

        name_index = AllocNameIndex(
            self.job_id, group, tg.count, untainted.union(migrate, reschedule_now)
        )

        canary_state = dstate is not None and dstate.desired_canaries != 0 and not dstate.promoted
        stop = self._compute_stop(
            tg, name_index, untainted, migrate, lost, canaries, canary_state
        )
        desired_changes.stop += len(stop)
        untainted = untainted.difference(stop)

        ignore2, inplace, destructive = self._compute_updates(tg, untainted)
        desired_changes.ignore += len(ignore2)
        desired_changes.in_place_update += len(inplace)
        if not existing_deployment:
            dstate.desired_total += len(destructive) + len(inplace)

        if canary_state:
            untainted = untainted.difference(canaries)

        num_destructive = len(destructive)
        strategy = tg.update
        canaries_promoted = dstate is not None and dstate.promoted
        require_canary = (
            num_destructive != 0
            and strategy is not None
            and len(canaries) < strategy.canary
            and not canaries_promoted
        )
        if require_canary and not self.deployment_paused and not self.deployment_failed:
            number = strategy.canary - len(canaries)
            desired_changes.canary += number
            if not existing_deployment:
                dstate.desired_canaries = strategy.canary
            for name in name_index.next_canaries(number, canaries, destructive):
                self.result.place.append(
                    AllocPlaceResult(name=name, canary=True, task_group=tg)
                )

        canary_state = dstate is not None and dstate.desired_canaries != 0 and not dstate.promoted
        limit = self._compute_limit(tg, untainted, destructive, migrate, canary_state)

        place = self._compute_placements(tg, name_index, untainted, migrate, reschedule_now)
        if not existing_deployment:
            dstate.desired_total += len(place)

        deployment_place_ready = (
            not self.deployment_paused and not self.deployment_failed and not canary_state
        )

        if deployment_place_ready:
            desired_changes.place += len(place)
            self.result.place.extend(place)
            self._mark_stop(reschedule_now, "", ALLOC_RESCHEDULED)
            desired_changes.stop += len(reschedule_now)
            limit -= min(len(place), limit)
        else:
            if lost:
                allowed = min(len(lost), len(place))
                desired_changes.place += allowed
                self.result.place.extend(place[:allowed])
            if reschedule_now:
                for p in place:
                    prev = p.get_previous_allocation()
                    if p.is_rescheduling() and not (
                        self.deployment_failed
                        and prev is not None
                        and self.deployment is not None
                        and self.deployment.id == prev.deployment_id
                    ):
                        self.result.place.append(p)
                        desired_changes.place += 1
                        self.result.stop.append(
                            AllocStopResult(alloc=prev, status_description=ALLOC_RESCHEDULED)
                        )
                        desired_changes.stop += 1

        if deployment_place_ready:
            dmin = min(len(destructive), limit)
            desired_changes.destructive_update += dmin
            desired_changes.ignore += len(destructive) - dmin
            for alloc in destructive.name_order()[:dmin]:
                self.result.destructive_update.append(
                    AllocDestructiveResult(
                        place_name=alloc.name,
                        place_task_group=tg,
                        stop_alloc=alloc,
                        stop_status_description=ALLOC_UPDATING,
                    )
                )
        else:
            desired_changes.ignore += len(destructive)

        desired_changes.migrate += len(migrate)
        for alloc in migrate.name_order():
            self.result.stop.append(
                AllocStopResult(alloc=alloc, status_description=ALLOC_MIGRATING)
            )
            self.result.place.append(
                AllocPlaceResult(
                    name=alloc.name, canary=False, task_group=tg, previous_alloc=alloc
                )
            )

        updating_spec = len(destructive) != 0 or len(self.result.inplace_update) != 0
        had_running = any(
            alloc.job is not None
            and alloc.job.version == self.job.version
            and alloc.job.create_index == self.job.create_index
            for alloc in all_allocs.values()
        )

        if (
            not existing_deployment
            and not _update_is_empty(strategy)
            and dstate.desired_total != 0
            and (not had_running or updating_spec)
        ):
            if self.deployment is None:
                self.deployment = new_deployment(self.job)
                self.result.deployment = self.deployment
            self.deployment.task_groups[group] = dstate

        deployment_complete = (
            len(destructive)
            + len(inplace)
            + len(place)
            + len(migrate)
            + len(reschedule_now)
            + len(reschedule_later)
            == 0
            and not require_canary
        )

        if deployment_complete and self.deployment is not None:
            ds = self.deployment.task_groups.get(group)
            if ds is not None:
                if ds.healthy_allocs < max(ds.desired_total, ds.desired_canaries) or (
                    ds.desired_canaries > 0 and not ds.promoted
                ):
                    deployment_complete = False

        return deployment_complete

    # ------------------------------------------------------------------

    def _filter_old_terminal_allocs(self, all_allocs: AllocSet) -> Tuple[AllocSet, AllocSet]:
        if not self.batch:
            return all_allocs, AllocSet()
        filtered, ignored = AllocSet(), AllocSet()
        for aid, alloc in all_allocs.items():
            older = alloc.job is not None and (
                alloc.job.version < self.job.version
                or alloc.job.create_index < self.job.create_index
            )
            if older and alloc.terminal_status():
                ignored[aid] = alloc
            else:
                filtered[aid] = alloc
        return filtered, ignored

    def _handle_group_canaries(
        self, all_allocs: AllocSet, desired_changes: DesiredUpdates
    ) -> Tuple[AllocSet, AllocSet]:
        stop: List[str] = []
        if self.old_deployment is not None:
            for s in self.old_deployment.task_groups.values():
                if not s.promoted:
                    stop.extend(s.placed_canaries)
        if self.deployment is not None and self.deployment.status == DEPLOYMENT_STATUS_FAILED:
            for s in self.deployment.task_groups.values():
                if not s.promoted:
                    stop.extend(s.placed_canaries)

        stop_set = all_allocs.from_keys(stop)
        self._mark_stop(stop_set, "", ALLOC_NOT_NEEDED)
        desired_changes.stop += len(stop_set)
        all_allocs = all_allocs.difference(stop_set)

        canaries = AllocSet()
        if self.deployment is not None:
            canary_ids: List[str] = []
            for s in self.deployment.task_groups.values():
                canary_ids.extend(s.placed_canaries)
            canaries = all_allocs.from_keys(canary_ids)
            untainted, migrate, lost = canaries.filter_by_tainted(self.tainted_nodes)
            self._mark_stop(migrate, "", ALLOC_MIGRATING)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            canaries = untainted
            all_allocs = all_allocs.difference(migrate, lost)

        return canaries, all_allocs

    def _compute_limit(
        self,
        group: TaskGroup,
        untainted: AllocSet,
        destructive: AllocSet,
        migrate: AllocSet,
        canary_state: bool,
    ) -> int:
        if _update_is_empty(group.update) or len(destructive) + len(migrate) == 0:
            return group.count
        if self.deployment_paused or self.deployment_failed:
            return 0
        if canary_state:
            return 0
        limit = group.update.max_parallel
        if self.deployment is not None:
            part_of, _ = untainted.filter_by_deployment(self.deployment.id)
            for alloc in part_of.values():
                if alloc.deployment_status is not None and alloc.deployment_status.is_unhealthy():
                    return 0
                if alloc.deployment_status is None or not alloc.deployment_status.is_healthy():
                    limit -= 1
        return max(limit, 0)

    def _compute_placements(
        self,
        group: TaskGroup,
        name_index: AllocNameIndex,
        untainted: AllocSet,
        migrate: AllocSet,
        reschedule: AllocSet,
    ) -> List[AllocPlaceResult]:
        place: List[AllocPlaceResult] = []
        for alloc in reschedule.values():
            place.append(
                AllocPlaceResult(
                    name=alloc.name,
                    task_group=group,
                    previous_alloc=alloc,
                    reschedule=True,
                    canary=alloc.deployment_status is not None
                    and alloc.deployment_status.canary,
                )
            )
        existing = len(untainted) + len(migrate) + len(reschedule)
        if existing < group.count:
            for name in name_index.next(group.count - existing):
                place.append(AllocPlaceResult(name=name, task_group=group))
        return place

    def _compute_stop(
        self,
        group: TaskGroup,
        name_index: AllocNameIndex,
        untainted: AllocSet,
        migrate: AllocSet,
        lost: AllocSet,
        canaries: AllocSet,
        canary_state: bool,
    ) -> AllocSet:
        stop = AllocSet()
        stop = stop.union(lost)
        self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)

        if canary_state:
            untainted = untainted.difference(canaries)

        remove = len(untainted) + len(migrate) - group.count
        if remove <= 0:
            return stop

        untainted = filter_by_terminal(untainted)

        if not canary_state and canaries:
            canary_names = canaries.name_set()
            for aid, alloc in list(untainted.difference(canaries).items()):
                if alloc.name in canary_names:
                    stop[aid] = alloc
                    self.result.stop.append(
                        AllocStopResult(alloc=alloc, status_description=ALLOC_NOT_NEEDED)
                    )
                    del untainted[aid]
                    remove -= 1
                    if remove == 0:
                        return stop

        if migrate:
            mnames = AllocNameIndex(self.job_id, group.name, group.count, migrate)
            remove_names = mnames.highest(remove)
            for aid, alloc in list(migrate.items()):
                if alloc.name not in remove_names:
                    continue
                self.result.stop.append(
                    AllocStopResult(alloc=alloc, status_description=ALLOC_NOT_NEEDED)
                )
                del migrate[aid]
                stop[aid] = alloc
                name_index.unset_index(alloc_index(alloc.name))
                remove -= 1
                if remove == 0:
                    return stop

        remove_names = name_index.highest(remove)
        for aid, alloc in list(untainted.items()):
            if alloc.name in remove_names:
                stop[aid] = alloc
                self.result.stop.append(
                    AllocStopResult(alloc=alloc, status_description=ALLOC_NOT_NEEDED)
                )
                del untainted[aid]
                remove -= 1
                if remove == 0:
                    return stop

        # Duplicate names fallback.
        for aid, alloc in list(untainted.items()):
            stop[aid] = alloc
            self.result.stop.append(
                AllocStopResult(alloc=alloc, status_description=ALLOC_NOT_NEEDED)
            )
            del untainted[aid]
            remove -= 1
            if remove == 0:
                return stop

        return stop

    def _compute_updates(
        self, group: TaskGroup, untainted: AllocSet
    ) -> Tuple[AllocSet, AllocSet, AllocSet]:
        ignore, inplace, destructive = AllocSet(), AllocSet(), AllocSet()
        for alloc in untainted.values():
            ignore_change, destructive_change, inplace_alloc = self.alloc_update_fn(
                alloc, self.job, group
            )
            if ignore_change:
                ignore[alloc.id] = alloc
            elif destructive_change:
                destructive[alloc.id] = alloc
            else:
                inplace[alloc.id] = alloc
                self.result.inplace_update.append(inplace_alloc)
        return ignore, inplace, destructive

    def _handle_delayed_reschedules(
        self,
        reschedule_later: List[DelayedRescheduleInfo],
        all_allocs: AllocSet,
        tg_name: str,
    ) -> None:
        if not reschedule_later:
            return

        reschedule_later.sort(key=lambda info: info.reschedule_time_ns)

        evals: List[Evaluation] = []
        next_resched_time = reschedule_later[0].reschedule_time_ns
        alloc_to_eval: Dict[str, str] = {}

        def make_eval(wait_until: int) -> Evaluation:
            return Evaluation(
                namespace=self.job.namespace,
                priority=self.job.priority,
                type=self.job.type,
                triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                job_id=self.job.id,
                job_modify_index=self.job.modify_index,
                status=EVAL_STATUS_PENDING,
                status_description=RESCHEDULING_FOLLOWUP_EVAL_DESC,
                wait_until_ns=wait_until,
            )

        current = make_eval(next_resched_time)
        evals.append(current)
        for info in reschedule_later:
            if info.reschedule_time_ns - next_resched_time < BATCHED_FAILED_ALLOC_WINDOW_NS:
                alloc_to_eval[info.alloc_id] = current.id
            else:
                next_resched_time = info.reschedule_time_ns
                current = make_eval(next_resched_time)
                evals.append(current)
                alloc_to_eval[info.alloc_id] = current.id

        self.result.desired_followup_evals[tg_name] = evals

        for alloc_id, eval_id in alloc_to_eval.items():
            existing = all_allocs[alloc_id]
            updated = existing.copy_skip_job()
            updated.followup_eval_id = eval_id
            self.result.attribute_updates[updated.id] = updated
