"""Reconciler helpers: alloc sets, name indexes, placement results.

Semantics follow reference ``scheduler/reconcile_util.go``.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_EVICT,
    ALLOC_DESIRED_STOP,
    Allocation,
    Deployment,
    Job,
    Node,
    TaskGroup,
)

_NAME_INDEX_RE = re.compile(r"\[(\d+)\]$")


def alloc_name(job: str, task_group: str, idx: int) -> str:
    return f"{job}.{task_group}[{idx}]"


def alloc_index(name: str) -> int:
    m = _NAME_INDEX_RE.search(name)
    return int(m.group(1)) if m else 0


# ---------------------------------------------------------------------------
# placement results
# ---------------------------------------------------------------------------


@dataclass
class AllocStopResult:
    alloc: Allocation
    client_status: str = ""
    status_description: str = ""


@dataclass
class AllocPlaceResult:
    """A new allocation to place."""

    name: str = ""
    canary: bool = False
    task_group: Optional[TaskGroup] = None
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False

    def get_task_group(self) -> TaskGroup:
        return self.task_group

    def get_name(self) -> str:
        return self.name

    def is_canary(self) -> bool:
        return self.canary

    def get_previous_allocation(self) -> Optional[Allocation]:
        return self.previous_alloc

    def is_rescheduling(self) -> bool:
        return self.reschedule

    def stop_previous_alloc(self) -> Tuple[bool, str]:
        return False, ""


@dataclass
class AllocDestructiveResult:
    """Stop the old alloc only once its replacement placed (atomic pair)."""

    place_name: str = ""
    place_task_group: Optional[TaskGroup] = None
    stop_alloc: Optional[Allocation] = None
    stop_status_description: str = ""

    def get_task_group(self) -> TaskGroup:
        return self.place_task_group

    def get_name(self) -> str:
        return self.place_name

    def is_canary(self) -> bool:
        return False

    def get_previous_allocation(self) -> Optional[Allocation]:
        return self.stop_alloc

    def is_rescheduling(self) -> bool:
        return False

    def stop_previous_alloc(self) -> Tuple[bool, str]:
        return True, self.stop_status_description


@dataclass
class DelayedRescheduleInfo:
    alloc_id: str
    alloc: Allocation
    reschedule_time_ns: int


# ---------------------------------------------------------------------------
# alloc sets
# ---------------------------------------------------------------------------


class AllocSet(Dict[str, Allocation]):
    """A set of allocations keyed by ID with reconcile helpers."""

    @classmethod
    def from_allocs(cls, allocs: Iterable[Allocation]) -> "AllocSet":
        s = cls()
        for a in allocs:
            s[a.id] = a
        return s

    def name_set(self) -> Set[str]:
        return {a.name for a in self.values()}

    def name_order(self) -> List[Allocation]:
        return sorted(self.values(), key=lambda a: alloc_index(a.name))

    def difference(self, *others: "AllocSet") -> "AllocSet":
        out = AllocSet()
        for k, v in self.items():
            if any(k in other for other in others):
                continue
            out[k] = v
        return out

    def union(self, *others: "AllocSet") -> "AllocSet":
        out = AllocSet(self)
        for other in others:
            out.update(other)
        return out

    def from_keys(self, keys: Iterable[str]) -> "AllocSet":
        out = AllocSet()
        for k in keys:
            if k in self:
                out[k] = self[k]
        return out

    def filter_by_tainted(
        self, nodes: Dict[str, Optional[Node]]
    ) -> Tuple["AllocSet", "AllocSet", "AllocSet"]:
        """(untainted, migrate, lost)."""
        untainted, migrate, lost = AllocSet(), AllocSet(), AllocSet()
        for alloc in self.values():
            if alloc.terminal_status():
                untainted[alloc.id] = alloc
                continue
            if alloc.desired_transition.should_migrate():
                migrate[alloc.id] = alloc
                continue
            if alloc.node_id not in nodes:
                untainted[alloc.id] = alloc
                continue
            n = nodes[alloc.node_id]
            if n is None or n.terminal_status():
                lost[alloc.id] = alloc
                continue
            untainted[alloc.id] = alloc
        return untainted, migrate, lost

    def filter_by_rescheduleable(
        self,
        is_batch: bool,
        now_ns: int,
        eval_id: str,
        deployment: Optional[Deployment],
    ) -> Tuple["AllocSet", "AllocSet", List[DelayedRescheduleInfo]]:
        """(untainted, reschedule_now, reschedule_later)."""
        untainted, reschedule_now = AllocSet(), AllocSet()
        reschedule_later: List[DelayedRescheduleInfo] = []
        for alloc in self.values():
            if alloc.next_allocation != "":
                continue
            is_untainted, ignore = should_filter(alloc, is_batch)
            if is_untainted:
                untainted[alloc.id] = alloc
            if is_untainted or ignore:
                continue
            eligible_now, eligible_later, reschedule_time = update_by_reschedulable(
                alloc, now_ns, eval_id, deployment
            )
            if not eligible_now:
                untainted[alloc.id] = alloc
                if eligible_later:
                    reschedule_later.append(
                        DelayedRescheduleInfo(alloc.id, alloc, reschedule_time)
                    )
            else:
                reschedule_now[alloc.id] = alloc
        return untainted, reschedule_now, reschedule_later

    def filter_by_deployment(self, deployment_id: str) -> Tuple["AllocSet", "AllocSet"]:
        match, nonmatch = AllocSet(), AllocSet()
        for alloc in self.values():
            if alloc.deployment_id == deployment_id:
                match[alloc.id] = alloc
            else:
                nonmatch[alloc.id] = alloc
        return match, nonmatch


def filter_by_terminal(allocs: AllocSet) -> AllocSet:
    out = AllocSet()
    for aid, alloc in allocs.items():
        if not alloc.terminal_status():
            out[aid] = alloc
    return out


def should_filter(alloc: Allocation, is_batch: bool) -> Tuple[bool, bool]:
    """(untainted, ignore) — reference reconcile_util.go shouldFilter."""
    if is_batch:
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            if alloc.ran_successfully():
                return True, False
            return False, True
        if alloc.client_status != ALLOC_CLIENT_FAILED:
            return True, False
        return False, False

    if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
        return False, True
    if alloc.client_status in (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_LOST):
        return False, True
    return False, False


RESCHEDULE_WINDOW_NS = 10**9  # 1s clock-drift guard


def update_by_reschedulable(
    alloc: Allocation, now_ns: int, eval_id: str, d: Optional[Deployment]
) -> Tuple[bool, bool, int]:
    """(reschedule_now, reschedule_later, reschedule_time_ns)."""
    if (
        d is not None
        and alloc.deployment_id == d.id
        and d.active()
        and not (alloc.desired_transition.reschedule is True)
    ):
        return False, False, 0

    reschedule_now = alloc.desired_transition.should_force_reschedule()

    reschedule_time, eligible = alloc.next_reschedule_time()
    if eligible and (
        alloc.followup_eval_id == eval_id or reschedule_time - now_ns <= RESCHEDULE_WINDOW_NS
    ):
        return True, False, reschedule_time
    if reschedule_now:
        return True, False, reschedule_time
    if eligible and alloc.followup_eval_id == "":
        return False, True, reschedule_time
    return False, False, reschedule_time


# ---------------------------------------------------------------------------
# name index
# ---------------------------------------------------------------------------


class AllocNameIndex:
    """Chooses allocation names (indexes) for placement/removal using a set
    of used indexes (reference uses a bitmap; a Python set is equivalent)."""

    def __init__(self, job: str, task_group: str, count: int, in_set: AllocSet) -> None:
        self.job = job
        self.task_group = task_group
        self.count = count
        self.used: Set[int] = {alloc_index(a.name) for a in in_set.values()}

    def highest(self, n: int) -> Set[str]:
        """Remove and return the highest n used names."""
        out: Set[str] = set()
        for idx in sorted(self.used, reverse=True):
            if len(out) >= n:
                break
            self.used.discard(idx)
            out.add(alloc_name(self.job, self.task_group, idx))
        return out

    def set_allocs(self, allocs: AllocSet) -> None:
        for a in allocs.values():
            self.used.add(alloc_index(a.name))

    def unset_index(self, idx: int) -> None:
        self.used.discard(idx)

    def next(self, n: int) -> List[str]:
        out: List[str] = []
        for idx in range(self.count):
            if len(out) == n:
                return out
            if idx not in self.used:
                out.append(alloc_name(self.job, self.task_group, idx))
                self.used.add(idx)
        i = 0
        while len(out) < n:
            out.append(alloc_name(self.job, self.task_group, i))
            self.used.add(i)
            i += 1
        return out

    def next_canaries(self, n: int, existing: AllocSet, destructive: AllocSet) -> List[str]:
        next_names: List[str] = []
        existing_names = existing.name_set()

        # Prefer indexes undergoing destructive updates (they'll be replaced).
        dused = {alloc_index(a.name) for a in destructive.values()}
        for idx in sorted(dused):
            if idx >= self.count:
                continue
            name = alloc_name(self.job, self.task_group, idx)
            if name not in existing_names:
                next_names.append(name)
                self.used.add(idx)
                if len(next_names) == n:
                    return next_names

        for idx in range(self.count):
            if idx in self.used:
                continue
            name = alloc_name(self.job, self.task_group, idx)
            if name not in existing_names:
                next_names.append(name)
                self.used.add(idx)
                if len(next_names) == n:
                    return next_names

        # Exhausted: extend past count to avoid overlap.
        i = self.count
        while len(next_names) < n:
            next_names.append(alloc_name(self.job, self.task_group, i))
            i += 1
        return next_names


def new_alloc_matrix(job: Optional[Job], allocs: List[Allocation]) -> Dict[str, AllocSet]:
    m: Dict[str, AllocSet] = {}
    for a in allocs:
        m.setdefault(a.task_group, AllocSet())[a.id] = a
    if job is not None:
        for tg in job.task_groups:
            m.setdefault(tg.name, AllocSet())
    return m
