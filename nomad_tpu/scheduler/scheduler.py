"""Scheduler interfaces and factory (reference ``scheduler/scheduler.go``).

State and Planner are duck-typed protocols here. State is any object with the
StateStore read surface (nodes, allocs_by_job, node_by_id, job_by_id,
latest_deployment_by_job_id, scheduler_config, allocs_by_node_terminal).
Planner must provide submit_plan / update_eval / create_eval / reblock_eval.
"""
from __future__ import annotations

from typing import Callable, Dict

SCHEDULER_VERSION = 1


class Planner:
    """Protocol for plan submission (reference scheduler.go:97)."""

    def submit_plan(self, plan):  # -> (PlanResult, Optional[State])
        raise NotImplementedError

    def update_eval(self, evaluation) -> None:
        raise NotImplementedError

    def create_eval(self, evaluation) -> None:
        raise NotImplementedError

    def reblock_eval(self, evaluation) -> None:
        raise NotImplementedError


def builtin_schedulers() -> Dict[str, Callable]:
    from .generic_sched import new_batch_scheduler, new_service_scheduler
    from .system_sched import new_system_scheduler

    return {
        "service": new_service_scheduler,
        "batch": new_batch_scheduler,
        "system": new_system_scheduler,
    }


def new_scheduler(name: str, logger, state, planner):
    factories = builtin_schedulers()
    if name not in factories:
        raise ValueError(f"unknown scheduler '{name}'")
    return factories[name](logger, state, planner)
