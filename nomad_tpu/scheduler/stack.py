"""Placement stacks: the chained iterator pipelines.

Semantics follow reference ``scheduler/stack.go`` and ``stack_oss.go``:
GenericStack = random source -> quota -> FeasibilityWrapper -> distinct_hosts
-> distinct_property -> rank -> binpack -> job-anti-affinity -> resched
penalty -> node affinity -> spread -> score-normalize -> limit(log2 N) ->
max-score. SystemStack = static source, no limit/max.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..structs.structs import Job, Node, TaskGroup
from .context import EvalContext
from .feasible import (
    ConstraintChecker,
    DeviceChecker,
    DistinctHostsIterator,
    DistinctPropertyIterator,
    DriverChecker,
    FeasibilityWrapper,
    HostVolumeChecker,
    QuotaIterator,
    StaticIterator,
    new_random_iterator,
)
from .rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    NodeAffinityIterator,
    NodeReschedulingPenaltyIterator,
    RankedNode,
    ScoreNormalizationIterator,
)
from .select import LimitIterator, MaxScoreIterator
from .spread import SpreadIterator
from .util import task_group_constraints

# Limit-iterator skip tuning (reference stack.go:14-17)
SKIP_SCORE_THRESHOLD = 0.0
MAX_SKIP = 3


@dataclass
class SelectOptions:
    penalty_node_ids: Set[str] = field(default_factory=set)
    preferred_nodes: List[Node] = field(default_factory=list)
    preempt: bool = False


class GenericStack:
    def __init__(self, batch: bool, ctx: EvalContext) -> None:
        self.batch = batch
        self.ctx = ctx

        self.source = new_random_iterator(ctx, [])
        self.quota = QuotaIterator(ctx, self.source)
        self.job_constraint = ConstraintChecker(ctx, None)
        self.task_group_drivers = DriverChecker(ctx, None)
        self.task_group_constraint = ConstraintChecker(ctx, None)
        self.task_group_devices = DeviceChecker(ctx)
        self.task_group_host_volumes = HostVolumeChecker(ctx)

        jobs = [self.job_constraint]
        tgs = [
            self.task_group_drivers,
            self.task_group_constraint,
            self.task_group_host_volumes,
            self.task_group_devices,
        ]
        self.wrapped_checks = FeasibilityWrapper(ctx, self.quota, jobs, tgs)
        self.distinct_hosts_constraint = DistinctHostsIterator(ctx, self.wrapped_checks)
        self.distinct_property_constraint = DistinctPropertyIterator(
            ctx, self.distinct_hosts_constraint
        )
        rank_source = FeasibleRankIterator(ctx, self.distinct_property_constraint)
        self.bin_pack = BinPackIterator(ctx, rank_source, False, 0)
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.bin_pack, "")
        self.node_rescheduling_penalty = NodeReschedulingPenaltyIterator(ctx, self.job_anti_aff)
        self.node_affinity = NodeAffinityIterator(ctx, self.node_rescheduling_penalty)
        self.spread = SpreadIterator(ctx, self.node_affinity)
        self.score_norm = ScoreNormalizationIterator(ctx, self.spread)
        self.limit = LimitIterator(ctx, self.score_norm, 2, SKIP_SCORE_THRESHOLD, MAX_SKIP)
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, base_nodes: List[Node]) -> None:
        if not self.ctx.deterministic:
            from .util import shuffle_nodes

            shuffle_nodes(base_nodes)
        self.source.set_nodes(base_nodes)
        if self.ctx.deterministic and self.ctx.ring_seed and base_nodes:
            # per-eval ring start (the deterministic shuffle analog;
            # see EvalContext.ring_seed)
            self.source.offset = self.ctx.ring_seed % len(base_nodes)

        # Candidate sampling bound: batch = power-of-two-choices, service =
        # ceil(log2 N) with a floor of 2 (reference stack.go:74-86).
        limit = 2
        n = len(base_nodes)
        if not self.batch and n > 0:
            log_limit = int(math.ceil(math.log2(n)))
            if log_limit > limit:
                limit = log_limit
        self.limit.set_limit(limit)

    def set_job(self, job: Job) -> None:
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_hosts_constraint.set_job(job)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_job(job)
        self.job_anti_aff.set_job(job)
        self.node_affinity.set_job(job)
        self.spread.set_job(job)
        self.ctx.get_eligibility().set_job(job)

    def select(self, tg: TaskGroup, options: Optional[SelectOptions]) -> Optional[RankedNode]:
        # Preferred-node pass first (sticky ephemeral disk).
        if options is not None and options.preferred_nodes:
            original_nodes = self.source.nodes
            self.source.set_nodes(list(options.preferred_nodes))
            options_new = SelectOptions(
                penalty_node_ids=options.penalty_node_ids,
                preferred_nodes=[],
                preempt=options.preempt,
            )
            option = self.select(tg, options_new)
            self.source.set_nodes(original_nodes)
            if option is not None:
                return option
            return self.select(tg, options_new)

        self.max_score.reset()
        self.ctx.reset()
        start = time.monotonic_ns()

        tg_constr = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(tg_constr.drivers)
        self.task_group_constraint.set_constraints(tg_constr.constraints)
        self.task_group_devices.set_task_group(tg)
        self.task_group_host_volumes.set_volumes(tg.volumes)
        self.distinct_hosts_constraint.set_task_group(tg)
        self.distinct_property_constraint.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)
        if options is not None:
            self.bin_pack.evict = options.preempt
        self.job_anti_aff.set_task_group(tg)
        if options is not None:
            self.node_rescheduling_penalty.set_penalty_nodes(options.penalty_node_ids)
        self.node_affinity.set_task_group(tg)
        self.spread.set_task_group(tg)

        if self.node_affinity.has_affinities() or self.spread.has_spreads():
            self.limit.set_limit(2**31 - 1)

        option = self.max_score.next()
        self.ctx.metrics.allocation_time_ns = time.monotonic_ns() - start
        return option


class SystemStack:
    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.source = StaticIterator(ctx, [])
        self.quota = QuotaIterator(ctx, self.source)
        self.job_constraint = ConstraintChecker(ctx, None)
        self.task_group_drivers = DriverChecker(ctx, None)
        self.task_group_constraint = ConstraintChecker(ctx, None)
        self.task_group_host_volumes = HostVolumeChecker(ctx)
        self.task_group_devices = DeviceChecker(ctx)

        jobs = [self.job_constraint]
        tgs = [
            self.task_group_drivers,
            self.task_group_constraint,
            self.task_group_host_volumes,
            self.task_group_devices,
        ]
        self.wrapped_checks = FeasibilityWrapper(ctx, self.quota, jobs, tgs)
        self.distinct_property_constraint = DistinctPropertyIterator(ctx, self.wrapped_checks)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property_constraint)

        _, sched_config = ctx.state.scheduler_config()
        enable_preemption = True
        if sched_config is not None:
            enable_preemption = sched_config.preemption_config.system_scheduler_enabled
        self.bin_pack = BinPackIterator(ctx, rank_source, enable_preemption, 0)
        self.score_norm = ScoreNormalizationIterator(ctx, self.bin_pack)

    def set_nodes(self, base_nodes: List[Node]) -> None:
        self.source.set_nodes(base_nodes)

    def set_job(self, job: Job) -> None:
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_job(job)
        self.ctx.get_eligibility().set_job(job)

    def select(self, tg: TaskGroup, options: Optional[SelectOptions]) -> Optional[RankedNode]:
        self.score_norm.reset()
        self.ctx.reset()
        start = time.monotonic_ns()

        tg_constr = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(tg_constr.drivers)
        self.task_group_constraint.set_constraints(tg_constr.constraints)
        self.task_group_devices.set_task_group(tg)
        self.task_group_host_volumes.set_volumes(tg.volumes)
        self.wrapped_checks.set_task_group(tg.name)
        self.distinct_property_constraint.set_task_group(tg)
        self.bin_pack.set_task_group(tg)

        option = self.score_norm.next()
        self.ctx.metrics.allocation_time_ns = time.monotonic_ns() - start
        return option
