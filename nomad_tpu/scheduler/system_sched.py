"""SystemScheduler: one alloc per eligible node.

Semantics follow reference ``scheduler/system_sched.go`` — Process :54,
computeJobAllocs :183, computePlacements :268, addBlocked :406.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ..structs.funcs import filter_terminal_allocs
from ..structs.structs import (
    ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_RUN,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_ALLOC_STOP,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    EVAL_TRIGGER_FAILED_FOLLOW_UP,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_DRAIN,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_PREEMPTION,
    EVAL_TRIGGER_QUEUED_ALLOCS,
    EVAL_TRIGGER_ROLLING_UPDATE,
    AllocMetric,
    AllocatedResources,
    AllocatedSharedResources,
    Allocation,
    Evaluation,
    Node,
)
from .context import EvalContext
from .stack import SystemStack
from .util import (
    ALLOC_LOST,
    ALLOC_NODE_TAINTED,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    BLOCKED_EVAL_FAILED_PLACEMENTS,
    SetStatusError,
    adjust_queued_allocations,
    desired_updates,
    diff_system_allocs,
    evict_and_place,
    inplace_update,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5

_VALID_TRIGGERS = {
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_FAILED_FOLLOW_UP,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_ROLLING_UPDATE,
    EVAL_TRIGGER_PREEMPTION,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    EVAL_TRIGGER_NODE_DRAIN,
    EVAL_TRIGGER_ALLOC_STOP,
    EVAL_TRIGGER_QUEUED_ALLOCS,
}


class SystemScheduler:
    def __init__(self, logger, state, planner, deterministic: bool = False) -> None:
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler.system")
        self.state = state
        self.planner = planner
        self.deterministic = deterministic

        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.nodes: List[Node] = []
        self.nodes_by_dc: Dict[str, int] = {}
        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, AllocMetric]] = None
        self.queued_allocs: Dict[str, int] = {}

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation

        if evaluation.triggered_by not in _VALID_TRIGGERS:
            desc = f"scheduler cannot handle '{evaluation.triggered_by}' evaluation reason"
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, None,
                self.failed_tg_allocs, EVAL_STATUS_FAILED, desc, self.queued_allocs, "",
            )
            return

        try:
            retry_max(
                MAX_SYSTEM_SCHEDULE_ATTEMPTS, self._process,
                lambda: progress_made(self.plan_result),
            )
        except SetStatusError as err:
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, None,
                self.failed_tg_allocs, err.eval_status, str(err), self.queued_allocs, "",
            )
            return

        set_status(
            self.logger, self.planner, self.eval, self.next_eval, None,
            self.failed_tg_allocs, EVAL_STATUS_COMPLETE, "", self.queued_allocs, "",
        )

    def _process(self) -> bool:
        self.job = self.state.job_by_id(self.eval.namespace, self.eval.job_id)
        self.queued_allocs = {}

        if self.job is not None and not self.job.stopped():
            self.nodes, self.nodes_by_dc = ready_nodes_in_dcs(
                self.state, self.job.datacenters
            )

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = None
        self.ctx = EvalContext(self.state, self.plan, self.logger,
                               deterministic=self.deterministic)
        self.stack = SystemStack(self.ctx)
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if self.plan.is_noop() and not self.eval.annotate_plan:
            return True

        if self.limit_reached and self.next_eval is None:
            stagger = self.job.update.stagger_ns if self.job.update else 0
            self.next_eval = self.eval.next_rolling_eval(stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, _, _ = result.full_commit(self.plan)
        if not full_commit:
            return False
        return True

    def _compute_job_allocs(self) -> None:
        allocs = self.state.allocs_by_job(self.eval.namespace, self.eval.job_id, True)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        allocs, terminal_allocs = filter_terminal_allocs(allocs)
        diff = diff_system_allocs(self.job, self.nodes, tainted, allocs, terminal_allocs)

        for e in diff.stop:
            self.plan.append_stopped_alloc(e.alloc, ALLOC_NOT_NEEDED, "")
        for e in diff.migrate:
            self.plan.append_stopped_alloc(e.alloc, ALLOC_NODE_TAINTED, "")
        for e in diff.lost:
            self.plan.append_stopped_alloc(e.alloc, ALLOC_LOST, ALLOC_CLIENT_LOST)

        destructive_updates, inplace_updates = inplace_update(
            self.ctx, self.eval, self.job, self.stack, diff.update
        )
        diff.update = destructive_updates

        if self.eval.annotate_plan:
            from ..structs.structs import PlanAnnotations

            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=desired_updates(diff, inplace_updates, destructive_updates)
            )

        limit = [len(diff.update)]
        if self.job is not None and not self.job.stopped() and self.job.update is not None \
                and self.job.update.rolling():
            limit = [self.job.update.max_parallel]

        self.limit_reached = evict_and_place(self.ctx, diff, diff.update, ALLOC_UPDATING, limit)

        if not diff.place:
            if self.job is not None and not self.job.stopped():
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1
            )

        self._compute_placements(diff.place)

    def _compute_placements(self, place) -> None:
        # tpu_binpack: one dense forced-node pass over the whole placement
        # list (the system analog of the generic engine path). The host
        # loop below remains the semantically complete fallback (and the
        # preemption path).
        from ..structs.structs import (
            SCHED_ALG_TPU_BINPACK,
            SCHED_ALG_TPU_BINPACK_CHUNKED,
        )

        _, sched_config = self.state.scheduler_config()
        # the chunked tier only changes the generic scheduler's scan; the
        # system forced-node pass is already one dense dispatch and stays
        # on the bit-parity kernel under either algorithm
        if sched_config is not None and sched_config.scheduler_algorithm in (
            SCHED_ALG_TPU_BINPACK,
            SCHED_ALG_TPU_BINPACK_CHUNKED,
        ):
            from ..tpu.integration import compute_system_placements_with_engine

            from ..trace import lifecycle as _trace_lc

            res = compute_system_placements_with_engine(self, place, sched_config)
            if res is True:
                _trace_lc.set_path(self.eval.id, "device")
                # device-built system plan: async-pipeline eligible (the
                # applier's eligibility shape-check still excludes plans
                # carrying stops/preemptions)
                self.plan.async_ok = True
                return
            if isinstance(res, list):
                # the device committed every clean placement; only the
                # preemption-needing nodes fall through to the host
                # per-node stack below (BinPackIterator evict path)
                place = res

        from ..trace import lifecycle as _trace_lc
        from ..utils import phases as _phases

        _trace_lc.set_path(self.eval.id, "host")
        with _phases.track("place"):
            self._host_placement_loop(place)

    def _host_placement_loop(self, place) -> None:
        node_by_id = {node.id: node for node in self.nodes}

        for missing in place:
            node = node_by_id.get(missing.alloc.node_id)
            if node is None:
                raise KeyError(f"could not find node {missing.alloc.node_id!r}")

            self.stack.set_nodes([node])
            option = self.stack.select(missing.task_group, None)

            if option is None:
                if self.ctx.metrics.nodes_filtered > 0:
                    # Constraint mismatch on this node: not a failure, the node
                    # just isn't in the job's domain.
                    self.queued_allocs[missing.task_group.name] -= 1
                    if (
                        self.eval.annotate_plan
                        and self.plan.annotations is not None
                        and missing.task_group.name in self.plan.annotations.desired_tg_updates
                    ):
                        self.plan.annotations.desired_tg_updates[
                            missing.task_group.name
                        ].place -= 1
                    continue

                if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                    self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
                    continue

                self.ctx.metrics.nodes_available = self.nodes_by_dc
                self.ctx.metrics.populate_score_meta_data()
                if self.failed_tg_allocs is None:
                    self.failed_tg_allocs = {}
                self.failed_tg_allocs[missing.task_group.name] = self.ctx.metrics
                self._add_blocked(node)
                continue

            self.ctx.metrics.nodes_available = self.nodes_by_dc
            self.ctx.metrics.populate_score_meta_data()

            resources = AllocatedResources(
                tasks=dict(option.task_resources),
                shared=AllocatedSharedResources(
                    disk_mb=missing.task_group.ephemeral_disk.size_mb
                ),
            )
            if option.alloc_resources is not None:
                resources.shared.networks = option.alloc_resources.networks

            alloc = Allocation(
                namespace=self.job.namespace,
                eval_id=self.eval.id,
                name=missing.name,
                job_id=self.job.id,
                task_group=missing.task_group.name,
                metrics=self.ctx.metrics,
                node_id=option.node.id,
                node_name=option.node.name,
                allocated_resources=resources,
                desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_PENDING,
            )

            if missing.alloc is not None and missing.alloc.id:
                alloc.previous_allocation = missing.alloc.id

            if option.preempted_allocs is not None:
                preempted_ids = []
                for stop in option.preempted_allocs:
                    self.plan.append_preempted_alloc(stop, alloc.id)
                    preempted_ids.append(stop.id)
                alloc.preempted_allocations = preempted_ids

            self.plan.append_alloc(alloc)

    def _add_blocked(self, node: Node) -> None:
        e = self.ctx.get_eligibility()
        escaped = e.has_escaped()
        class_eligibility = None if escaped else e.get_classes()
        blocked = self.eval.create_blocked_eval(class_eligibility, escaped, e.quota_limit_reached())
        blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        blocked.node_id = node.id
        self.planner.create_eval(blocked)


def new_system_scheduler(logger, state, planner):
    return SystemScheduler(logger, state, planner)
