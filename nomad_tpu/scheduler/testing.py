"""Scheduler test harness (reference ``scheduler/testing.go:42``).

A real in-memory StateStore plus a fake Planner that applies plans
synchronously — the parity oracle for host-vs-TPU plan diffing.
"""
from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from ..state import StateStore
from ..structs.structs import Evaluation, Plan, PlanResult
from .scheduler import new_scheduler


class Harness:
    def __init__(self, state: Optional[StateStore] = None) -> None:
        self.state = state or StateStore()
        self.planner = None  # optional custom planner override
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self._lock = threading.Lock()
        self._next_index = 1
        self.logger = logging.getLogger("nomad_tpu.scheduler.harness")

    def next_index(self) -> int:
        with self._lock:
            idx = self._next_index
            self._next_index += 1
            return idx

    # -- Planner -----------------------------------------------------------

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[StateStore]]:
        if self.planner is not None:
            self.plans.append(plan)
            return self.planner.submit_plan(plan)

        # The harness applies plans as classic per-alloc objects so tests
        # (the host-vs-TPU parity oracle above all) diff one shape.
        plan.inflate_dense()
        self.plans.append(plan)

        index = self.next_index()

        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=index,
        )

        # Stamp indexes + re-attach the plan's job the way shared pointers do
        # in the reference (UpsertPlanResults mutates the same structs).
        allocs_updated = []
        for alloc_list in plan.node_allocation.values():
            for alloc in alloc_list:
                existing = self.state.alloc_by_id(alloc.id)
                alloc.create_index = existing.create_index if existing else index
                alloc.modify_index = index
                if alloc.job is None:
                    alloc.job = plan.job
                allocs_updated.append(alloc)
        allocs_stopped = []
        for alloc_list in plan.node_update.values():
            for alloc in alloc_list:
                alloc.modify_index = index
                allocs_stopped.append(alloc)
        allocs_preempted = []
        for alloc_list in plan.node_preemptions.values():
            for alloc in alloc_list:
                alloc.modify_index = index
                allocs_preempted.append(alloc)

        self.state.upsert_plan_results(
            index,
            alloc_updates=allocs_updated,
            allocs_stopped=allocs_stopped,
            allocs_preempted=allocs_preempted,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            eval_id=plan.eval_id,
        )
        return result, None

    def update_eval(self, evaluation: Evaluation) -> None:
        self.evals.append(evaluation)
        if self.planner is not None:
            self.planner.update_eval(evaluation)

    def create_eval(self, evaluation: Evaluation) -> None:
        self.create_evals.append(evaluation)
        if self.planner is not None:
            self.planner.create_eval(evaluation)

    def reblock_eval(self, evaluation: Evaluation) -> None:
        self.reblock_evals.append(evaluation)
        if self.planner is not None:
            self.planner.reblock_eval(evaluation)

    # -- driving -----------------------------------------------------------

    def snapshot(self) -> StateStore:
        return self.state.snapshot()

    def process(self, scheduler_name: str, evaluation: Evaluation,
                deterministic: bool = True) -> None:
        """Process an eval with a scheduler created against a state snapshot."""
        sched = new_scheduler(scheduler_name, self.logger, self.snapshot(), self)
        if hasattr(sched, "deterministic"):
            sched.deterministic = deterministic
        sched.process(evaluation)

    def assert_eval_status(self, expected: str) -> None:
        assert len(self.evals) == 1, f"expected one eval update, got {len(self.evals)}"
        assert self.evals[0].status == expected, (
            f"expected status {expected}, got {self.evals[0].status}"
        )
