"""Scheduler helpers (reference ``scheduler/util.go``)."""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..structs.structs import (
    ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_STOP,
    EVAL_STATUS_FAILED,
    JOB_TYPE_BATCH,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    NODE_SCHED_ELIGIBLE,
    Allocation,
    AllocatedResources,
    AllocatedSharedResources,
    Constraint,
    Job,
    Node,
    Plan,
    PlanResult,
    TaskGroup,
)

ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"
RESCHEDULING_FOLLOWUP_EVAL_DESC = "created for delayed rescheduling"
MAX_PAST_RESCHEDULE_EVENTS = 5


class SetStatusError(Exception):
    def __init__(self, msg: str, eval_status: str = EVAL_STATUS_FAILED):
        super().__init__(msg)
        self.eval_status = eval_status


@dataclass
class AllocTuple:
    name: str
    task_group: Optional[TaskGroup]
    alloc: Optional[Allocation]


@dataclass
class DiffResult:
    place: List[AllocTuple] = field(default_factory=list)
    update: List[AllocTuple] = field(default_factory=list)
    migrate: List[AllocTuple] = field(default_factory=list)
    stop: List[AllocTuple] = field(default_factory=list)
    ignore: List[AllocTuple] = field(default_factory=list)
    lost: List[AllocTuple] = field(default_factory=list)

    def append(self, other: "DiffResult") -> None:
        self.place.extend(other.place)
        self.update.extend(other.update)
        self.migrate.extend(other.migrate)
        self.stop.extend(other.stop)
        self.ignore.extend(other.ignore)
        self.lost.extend(other.lost)


def materialize_task_groups(job: Optional[Job]) -> Dict[str, TaskGroup]:
    """Expand counts to named instances: "<job>.<tg>[i]"."""
    out: Dict[str, TaskGroup] = {}
    if job is None or job.stopped():
        return out
    for tg in job.task_groups:
        for i in range(tg.count):
            out[f"{job.name}.{tg.name}[{i}]"] = tg
    return out


def diff_allocs(
    job: Job,
    tainted_nodes: Dict[str, Optional[Node]],
    required: Dict[str, TaskGroup],
    allocs: List[Allocation],
    terminal_allocs: Dict[str, Allocation],
) -> DiffResult:
    """Set-difference of desired vs existing allocs (reference util.go:70)."""
    result = DiffResult()
    existing = set()
    for exist in allocs:
        name = exist.name
        existing.add(name)
        tg = required.get(name)
        if tg is None:
            result.stop.append(AllocTuple(name, tg, exist))
            continue
        if not exist.terminal_status() and exist.desired_transition.should_migrate():
            result.migrate.append(AllocTuple(name, tg, exist))
            continue
        if exist.node_id in tainted_nodes:
            node = tainted_nodes[exist.node_id]
            if exist.job is not None and exist.job.type == JOB_TYPE_BATCH and exist.ran_successfully():
                result.ignore.append(AllocTuple(name, tg, exist))
                continue
            if not exist.terminal_status() and (node is None or node.terminal_status()):
                result.lost.append(AllocTuple(name, tg, exist))
            else:
                result.ignore.append(AllocTuple(name, tg, exist))
            continue
        if exist.job is not None and job.job_modify_index != exist.job.job_modify_index:
            result.update.append(AllocTuple(name, tg, exist))
            continue
        result.ignore.append(AllocTuple(name, tg, exist))
    for name, tg in required.items():
        if name not in existing:
            result.place.append(AllocTuple(name, tg, terminal_allocs.get(name)))
    return result


def diff_system_allocs(
    job: Job,
    nodes: List[Node],
    tainted_nodes: Dict[str, Optional[Node]],
    allocs: List[Allocation],
    terminal_allocs: Dict[str, Allocation],
) -> DiffResult:
    """Per-node variant for the system scheduler (reference util.go:176)."""
    node_allocs: Dict[str, List[Allocation]] = {}
    for alloc in allocs:
        node_allocs.setdefault(alloc.node_id, []).append(alloc)
    for node in nodes:
        node_allocs.setdefault(node.id, [])

    required = materialize_task_groups(job)
    result = DiffResult()
    for node_id, nallocs in node_allocs.items():
        diff = diff_allocs(job, tainted_nodes, required, nallocs, terminal_allocs)
        if node_id in tainted_nodes:
            diff.place = []
        else:
            for tup in diff.place:
                if tup.alloc is None or tup.alloc.node_id != node_id:
                    tup.alloc = Allocation(node_id=node_id)
        result.append(diff)
    return result


# (store_id, node_epoch, dcs) -> (nodes, dc_map). Node objects are
# immutable-once-stored and shared across snapshots, so reusing the
# filtered list across the many evals between node-table writes is safe;
# callers get copies because the stack may shuffle in place.
_READY_NODES_CACHE: Dict[tuple, Tuple[List[Node], Dict[str, int]]] = {}
_READY_NODES_CACHE_MAX = 16


def ready_nodes_in_dcs(state, dcs: List[str]) -> Tuple[List[Node], Dict[str, int]]:
    key = None
    store_id = getattr(state, "store_id", None)
    if store_id is not None:
        key = (store_id, state.node_epoch, tuple(dcs))
        hit = _READY_NODES_CACHE.get(key)
        if hit is not None:
            return list(hit[0]), dict(hit[1])
    dc_map = {dc: 0 for dc in dcs}
    out = []
    for node in state.nodes():
        if node.status != NODE_STATUS_READY:
            continue
        if node.drain:
            continue
        if node.scheduling_eligibility != NODE_SCHED_ELIGIBLE:
            continue
        if node.datacenter not in dc_map:
            continue
        out.append(node)
        dc_map[node.datacenter] += 1
    if key is not None:
        if len(_READY_NODES_CACHE) >= _READY_NODES_CACHE_MAX:
            _READY_NODES_CACHE.clear()
        _READY_NODES_CACHE[key] = (out, dc_map)
        return list(out), dict(dc_map)
    return out, dc_map


def retry_max(max_attempts: int, cb: Callable[[], bool], reset: Optional[Callable[[], bool]] = None) -> None:
    """Retry until cb() returns done; reset() returning True restarts attempts."""
    attempts = 0
    while attempts < max_attempts:
        done = cb()
        if done:
            return
        if reset is not None and reset():
            attempts = 0
        else:
            attempts += 1
    raise SetStatusError(f"maximum attempts reached ({max_attempts})")


def progress_made(result: Optional[PlanResult]) -> bool:
    return result is not None and (
        bool(result.node_update)
        or bool(result.node_allocation)
        or bool(result.dense_placements)
        or result.deployment is not None
        or bool(result.deployment_updates)
    )


def tainted_nodes(state, allocs: List[Allocation]) -> Dict[str, Optional[Node]]:
    """Nodes (down/draining/missing) containing these allocs (util.go:303)."""
    out: Dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.status == NODE_STATUS_DOWN or node.drain:
            out[alloc.node_id] = node
    return out


def shuffle_nodes(nodes: List[Node]) -> None:
    n = len(nodes)
    for i in range(n - 1, 0, -1):
        j = random.randint(0, i)
        nodes[i], nodes[j] = nodes[j], nodes[i]


def networks_updated(nets_a, nets_b) -> bool:
    if len(nets_a) != len(nets_b):
        return True
    for an, bn in zip(nets_a, nets_b):
        if an.mbits != bn.mbits:
            return True
        if _network_port_map(an) != _network_port_map(bn):
            return True
    return False


def _network_port_map(n) -> Dict[str, int]:
    m = {p.label: p.value for p in n.reserved_ports}
    m.update({p.label: -1 for p in n.dynamic_ports})
    return m


def _merged_affinities(job: Job, tg: TaskGroup):
    out = list(job.affinities) + list(tg.affinities)
    for task in tg.tasks:
        out.extend(task.affinities)
    return out


def tasks_updated(job_a: Job, job_b: Job, task_group: str) -> bool:
    """Whether the group requires a destructive update (reference util.go:342)."""
    a = job_a.lookup_task_group(task_group)
    b = job_b.lookup_task_group(task_group)
    if len(a.tasks) != len(b.tasks):
        return True
    if a.ephemeral_disk != b.ephemeral_disk:
        return True
    if networks_updated(a.networks, b.networks):
        return True
    if _merged_affinities(job_a, a) != _merged_affinities(job_b, b):
        return True
    if list(job_a.spreads) + list(a.spreads) != list(job_b.spreads) + list(b.spreads):
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver or at.user != bt.user:
            return True
        if at.config != bt.config or at.env != bt.env:
            return True
        if at.artifacts != bt.artifacts or at.vault != bt.vault or at.templates != bt.templates:
            return True
        if job_a.combined_task_meta(task_group, at.name) != job_b.combined_task_meta(task_group, bt.name):
            return True
        if networks_updated(at.resources.networks, bt.resources.networks):
            return True
        ar, br = at.resources, bt.resources
        if ar.cpu != br.cpu or ar.memory_mb != br.memory_mb or ar.devices != br.devices:
            return True
    return False


def set_status(
    logger,
    planner,
    eval,
    next_eval,
    spawned_blocked,
    tg_metrics,
    status: str,
    desc: str,
    queued_allocs,
    deployment_id: str,
) -> None:
    new_eval = eval.copy()
    new_eval.status = status
    new_eval.status_description = desc
    new_eval.deployment_id = deployment_id
    new_eval.failed_tg_allocs = tg_metrics or {}
    if next_eval is not None:
        new_eval.next_eval = next_eval.id
    if spawned_blocked is not None:
        new_eval.blocked_eval = spawned_blocked.id
    if queued_allocs is not None:
        new_eval.queued_allocations = queued_allocs
    planner.update_eval(new_eval)


def evict_and_place(ctx, diff: DiffResult, allocs: List[AllocTuple], desc: str, limit: List[int]) -> bool:
    """Stop up to limit[0] allocs and queue replacements; True if limit hit."""
    n = len(allocs)
    for i in range(min(n, limit[0])):
        a = allocs[i]
        ctx.plan.append_stopped_alloc(a.alloc, desc, "")
        diff.place.append(a)
    if n <= limit[0]:
        limit[0] -= n
        return False
    limit[0] = 0
    return True


@dataclass
class TgConstrainTuple:
    constraints: List[Constraint]
    drivers: set


def task_group_constraints(tg: TaskGroup) -> TgConstrainTuple:
    constraints = list(tg.constraints)
    drivers = set()
    for task in tg.tasks:
        drivers.add(task.driver)
        constraints.extend(task.constraints)
    return TgConstrainTuple(constraints=constraints, drivers=drivers)


def adjust_queued_allocations(logger, result: Optional[PlanResult], queued_allocs: Dict[str, int]) -> None:
    if result is None:
        return
    for allocations in result.node_allocation.values():
        for allocation in allocations:
            if allocation.create_index != allocation.modify_index:
                continue
            if allocation.task_group in queued_allocs:
                queued_allocs[allocation.task_group] -= 1
    for block in result.dense_placements:
        # dense blocks are fresh placements by construction (create==modify)
        if block.task_group in queued_allocs:
            queued_allocs[block.task_group] -= len(block.ids)


def update_non_terminal_allocs_to_lost(
    plan: Plan, tainted: Dict[str, Optional[Node]], allocs: List[Allocation]
) -> None:
    """Mark pending/running allocs on down nodes as lost (util.go:800)."""
    for alloc in allocs:
        if alloc.node_id not in tainted:
            continue
        node = tainted[alloc.node_id]
        if node is not None and node.status != NODE_STATUS_DOWN:
            continue
        if alloc.desired_status == ALLOC_DESIRED_STOP and alloc.client_status in (
            ALLOC_CLIENT_RUNNING,
            ALLOC_CLIENT_PENDING,
        ):
            from ..structs.structs import ALLOC_CLIENT_LOST

            plan.append_stopped_alloc(alloc, ALLOC_LOST, ALLOC_CLIENT_LOST)


def desired_updates(diff: DiffResult, inplace_updates, destructive_updates):
    from ..structs.structs import DesiredUpdates

    desired: Dict[str, DesiredUpdates] = {}

    def get(name: str) -> DesiredUpdates:
        return desired.setdefault(name, DesiredUpdates())

    for tup in diff.place:
        get(tup.task_group.name).place += 1
    for tup in diff.stop:
        get(tup.alloc.task_group).stop += 1
    for tup in diff.ignore:
        get(tup.task_group.name).ignore += 1
    for tup in diff.migrate:
        get(tup.task_group.name).migrate += 1
    for tup in inplace_updates:
        get(tup.task_group.name).in_place_update += 1
    for tup in destructive_updates:
        get(tup.task_group.name).destructive_update += 1
    return desired


def inplace_update(ctx, eval, job: Job, stack, updates: List[AllocTuple]):
    """Try to update allocs in place; returns (destructive, inplace)
    (reference util.go:539)."""
    ws_updates = list(updates)
    inplace: List[AllocTuple] = []
    destructive: List[AllocTuple] = []
    for update in ws_updates:
        existing = update.alloc.job
        if existing is None or tasks_updated(job, existing, update.task_group.name):
            destructive.append(update)
            continue
        if update.alloc.terminal_status():
            inplace.append(update)
            continue
        node = ctx.state.node_by_id(update.alloc.node_id)
        if node is None:
            destructive.append(update)
            continue
        stack.set_nodes([node])
        ctx.plan.append_stopped_alloc(update.alloc, ALLOC_IN_PLACE, "")
        option = stack.select(update.task_group, None)
        ctx.plan.pop_update(update.alloc)
        if option is None:
            destructive.append(update)
            continue
        for task, resources in option.task_resources.items():
            networks = []
            if update.alloc.allocated_resources is not None:
                tr = update.alloc.allocated_resources.tasks.get(task)
                if tr is not None:
                    networks = tr.networks
            resources.networks = networks
        new_alloc = update.alloc.copy_skip_job()
        new_alloc.eval_id = eval.id
        new_alloc.job = None
        new_alloc.allocated_resources = AllocatedResources(
            tasks=option.task_resources,
            shared=AllocatedSharedResources(disk_mb=update.task_group.ephemeral_disk.size_mb),
        )
        new_alloc.metrics = ctx.metrics
        ctx.plan.append_alloc(new_alloc)
        inplace.append(update)
    return destructive, inplace
