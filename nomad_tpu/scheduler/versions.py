"""Version parsing and constraint checking.

Implements the semantics of the reference's two version engines:
go-version (lenient, used by the ``version`` operand) and strict semver
(``semver`` operand) — reference scheduler/feasible.go:1170-1214 and
helper/constraints/semver/.
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)(?:-([0-9A-Za-z.-]+))?(?:\+([0-9A-Za-z.-]+))?$"
)
_SEMVER_RE = re.compile(
    r"^(\d+)\.(\d+)\.(\d+)(?:-([0-9A-Za-z.-]+))?(?:\+([0-9A-Za-z.-]+))?$"
)


class Version:
    __slots__ = ("segments", "prerelease", "_si")

    def __init__(self, segments: List[int], prerelease: str):
        self.segments = segments
        self.prerelease = prerelease
        self._si = len(segments)

    @classmethod
    def parse(cls, s: str, strict: bool = False) -> Optional["Version"]:
        s = s.strip()
        if strict:
            m = _SEMVER_RE.match(s)
            if not m:
                return None
            return cls([int(m.group(1)), int(m.group(2)), int(m.group(3))], m.group(4) or "")
        m = _VERSION_RE.match(s)
        if not m:
            return None
        segments = [int(x) for x in m.group(1).split(".")]
        while len(segments) < 3:
            segments.append(0)
        return cls(segments, m.group(2) or "")

    def _cmp_prerelease(self, other: "Version") -> int:
        a, b = self.prerelease, other.prerelease
        if a == b:
            return 0
        if a == "":
            return 1  # release > prerelease
        if b == "":
            return -1
        # dotted identifier comparison (numeric identifiers compare numerically)
        pa, pb = a.split("."), b.split(".")
        for xa, xb in zip(pa, pb):
            na, nb = xa.isdigit(), xb.isdigit()
            if na and nb:
                if int(xa) != int(xb):
                    return -1 if int(xa) < int(xb) else 1
            elif na != nb:
                return -1 if na else 1  # numeric < alphanumeric
            elif xa != xb:
                return -1 if xa < xb else 1
        if len(pa) != len(pb):
            return -1 if len(pa) < len(pb) else 1
        return 0

    def compare(self, other: "Version") -> int:
        n = max(len(self.segments), len(other.segments))
        for i in range(n):
            a = self.segments[i] if i < len(self.segments) else 0
            b = other.segments[i] if i < len(other.segments) else 0
            if a != b:
                return -1 if a < b else 1
        return self._cmp_prerelease(other)


_CONSTRAINT_RE = re.compile(r"^\s*(=|!=|>=|<=|>|<|~>)?\s*(.+?)\s*$")


class Constraints:
    """A parsed comma-separated constraint set (all must hold)."""

    def __init__(self, parts: List[Tuple[str, Version, int]]):
        self.parts = parts

    @classmethod
    def parse(cls, spec: str, strict: bool = False) -> Optional["Constraints"]:
        parts: List[Tuple[str, Version, int]] = []
        for raw in spec.split(","):
            m = _CONSTRAINT_RE.match(raw)
            if not m or not m.group(2):
                return None
            op = m.group(1) or "="
            vstr = m.group(2)
            # ~> keeps track of how many segments were specified
            seg_count = len(vstr.lstrip("v").split("-")[0].split("."))
            v = Version.parse(vstr, strict=strict)
            if v is None:
                return None
            parts.append((op, v, seg_count))
        return cls(parts) if parts else None

    def check(self, v: Version) -> bool:
        return all(self._check_one(op, target, segs, v) for op, target, segs in self.parts)

    @staticmethod
    def _check_one(op: str, target: Version, seg_count: int, v: Version) -> bool:
        c = v.compare(target)
        if op == "=":
            return c == 0
        if op == "!=":
            return c != 0
        if op == ">":
            return c > 0
        if op == "<":
            return c < 0
        if op == ">=":
            return c >= 0
        if op == "<=":
            return c <= 0
        if op == "~>":
            # pessimistic: >= target and < next significant release
            if c < 0:
                return False
            upper_segments = list(target.segments[: max(seg_count - 1, 1)])
            upper_segments[-1] += 1
            upper = Version(upper_segments, "")
            return v.compare(upper) < 0
        return False
