"""Server runtime (reference nomad/): raft/FSM, broker, planner, workers."""
from .eval_broker import EvalBroker  # noqa: F401
from .blocked_evals import BlockedEvals  # noqa: F401
from .fsm import NomadFSM  # noqa: F401
from .plan_apply import Planner, PlanQueue  # noqa: F401
from .raft import InProcRaft  # noqa: F401
from .server import Server, ServerConfig  # noqa: F401
from .worker import Worker  # noqa: F401
