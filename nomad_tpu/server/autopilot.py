"""Autopilot: server health tracking + dead-server cleanup.

Fills the role of reference ``nomad/autopilot.go`` (+ vendored
hashicorp/consul autopilot): the leader periodically scores every known
server's health (gossip liveness + raft replication lag) and, when
``cleanup_dead_servers`` is on, removes servers that gossip reports
failed — but only while a quorum of healthy voters remains, so cleanup
can never cause the loss of availability it exists to prevent. The
config is raft-replicated like SchedulerConfiguration and mutable at
runtime via /v1/operator/autopilot/configuration.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("nomad_tpu.autopilot")

AUTOPILOT_CONFIG = "autopilot-config"


@dataclass
class AutopilotConfig:
    """structs/operator.go AutopilotConfig."""

    cleanup_dead_servers: bool = True
    last_contact_threshold_s: float = 10.0
    server_stabilization_time_s: float = 10.0
    create_index: int = 0
    modify_index: int = 0


@dataclass
class ServerHealth:
    """structs/operator.go ServerHealth."""

    id: str = ""
    name: str = ""
    address: str = ""
    serf_status: str = "none"
    leader: bool = False
    voter: bool = True
    healthy: bool = False
    last_contact_s: float = -1.0
    last_index: int = 0
    stable_since: float = field(default_factory=time.monotonic)


class Autopilot:
    def __init__(self, server, membership=None, wire_raft=None,
                 interval: float = 2.0) -> None:
        self.server = server
        self.membership = membership
        self.wire_raft = wire_raft
        self.interval = interval
        self._health: Dict[str, ServerHealth] = {}
        # name → (raw_healthy, raw_since): stabilization clock input
        self._raw: Dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- config (raft-replicated) ----------------------------------------

    def config(self) -> AutopilotConfig:
        cfg = getattr(self.server.fsm.state, "autopilot_config_entry", None)
        return cfg if cfg is not None else AutopilotConfig()

    # -- health ----------------------------------------------------------

    def server_health(self) -> List[ServerHealth]:
        """Health snapshot for /v1/operator/autopilot/health."""
        out: List[ServerHealth] = []
        if self.membership is None:
            # single-server dev mode: we are trivially healthy
            return [ServerHealth(
                id=self.server.name, name=self.server.name,
                serf_status="alive", leader=self.server.is_leader,
                healthy=True, last_contact_s=0.0,
                last_index=self.server.fsm.state.latest_index,
            )]
        cfg = self.config()
        local_name = self.membership.memberlist.config.name
        members = {m.name: m for m in self.membership.members()}
        # health covers every nomad server gossip knows about — including
        # failed ones (the region map drops them; the operator must still
        # see WHY the cluster is degraded)
        from .membership import ServerMeta, _parse_server

        rows: Dict[str, ServerMeta] = {
            meta.name: meta for meta in self.membership.servers_in_region()
        }
        for member in members.values():
            if member.name in rows:
                continue
            meta = _parse_server(member)
            if meta is not None and meta.region == self.membership.region:
                rows[meta.name] = meta
        now = time.monotonic()
        for meta in rows.values():
            member = members.get(meta.name)
            serf_status = member.status if member is not None else "none"
            alive = serf_status == "alive"
            health = ServerHealth(
                id=meta.name,
                name=meta.name,
                address=f"{meta.rpc_host}:{meta.rpc_port}",
                serf_status=serf_status,
                leader=meta.is_leader,
                healthy=alive,
                last_contact_s=0.0 if alive else -1.0,
            )
            raw = alive
            if self.wire_raft is not None and self.server.is_leader:
                if meta.name == local_name:
                    health.last_index = self.wire_raft.commit_index
                else:
                    health.last_index = self.wire_raft.match_index.get(meta.name, 0)
                    lag = self.wire_raft.commit_index - health.last_index
                    if lag > 512:  # replication badly behind
                        raw = False
            # stabilization hold-down tracks RAW transitions (never the
            # reported value, which the hold-down itself suppresses — that
            # would reset the clock every tick and pin a recovered server
            # unhealthy forever). First sighting counts stable already.
            prev = self._raw.get(meta.name)
            if prev is None:
                since = now - cfg.server_stabilization_time_s
            elif prev[0] != raw:
                since = now
            else:
                since = prev[1]
            self._raw[meta.name] = (raw, since)
            health.stable_since = since
            health.healthy = raw and (now - since >= cfg.server_stabilization_time_s)
            out.append(health)
            self._health[meta.name] = health
        return out

    # -- dead server cleanup (autopilot.go pruneDeadServers) -------------

    def prune_dead_servers(self) -> List[str]:
        if (
            self.membership is None
            or self.wire_raft is None
            or not self.server.is_leader
            or not self.config().cleanup_dead_servers
        ):
            return []
        peers = dict(self.wire_raft.peers)
        cluster = len(peers) + 1
        quorum = cluster // 2 + 1
        alive = {m.name for m in self.membership.members() if m.status == "alive"}
        dead = [peer_id for peer_id in peers if peer_id not in alive]
        # never remove more servers than keeps a healthy quorum
        removable = max(0, cluster - quorum)
        removed = []
        remove = getattr(
            self.wire_raft, "remove_peer_replicated", self.wire_raft.remove_peer
        )
        for peer_id in dead[:removable]:
            logger.warning("autopilot removing dead server %s", peer_id)
            try:
                remove(peer_id)
            except Exception as e:  # noqa: BLE001 — e.g. lost leadership mid-prune
                logger.warning("removal of %s failed: %s", peer_id, e)
                continue
            removed.append(peer_id)
        return removed

    # -- loop ------------------------------------------------------------

    def start(self) -> "Autopilot":
        self._thread = threading.Thread(
            target=self._loop, name="autopilot", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.server_health()
                self.prune_dead_servers()
            except Exception:  # noqa: BLE001
                logger.exception("autopilot tick failed")

    def stop(self) -> None:
        self._stop.set()
