"""Leader-side autoscaler: capacity chases blocked demand.

Reference Nomad delegates this loop to an external autoscaler agent
watching ``/v1/metrics``; here the same policy runs as a leader task so
the saturated regime closes its own loop. Each tick reads
``BlockedEvals.stats()`` — the identical surface the external agent
scrapes as ``nomad.blocked_evals.*`` gauges — and drives the node fleet
through two callbacks the embedding harness supplies:

- ``scale_up_fn(n) -> int`` — provision and register up to ``n`` nodes,
  returning how many actually joined (each registration lands in the FSM
  and fires the capacity-change trigger, so the blocked evals storm out
  through the coalesced unblock path on their own);
- ``scale_down_fn(n) -> int`` — drain/retire up to ``n`` of the nodes
  this autoscaler added, returning how many.

Policy, deliberately simple (proportional step, rate-limited):

- *scale up* when blocked depth >= ``blocked_threshold``: request
  ``ceil(blocked / evals_per_node)`` nodes, capped at ``max_step``, at
  most once per ``cooldown_s``;
- *scale down* after ``drain_idle_ticks`` consecutive ticks with zero
  blocked evals, stepping back at most ``max_step`` of its own nodes per
  cooldown — capacity it never added is never drained.

Armed/disarmed with leadership like the watchdog and flight recorder:
followers hold a disabled instance, and `set_enabled(False)` resets the
burst state so a re-elected leader starts from a clean cooldown.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..trace import capacity
from ..utils import metrics
from ..utils.lock_witness import witness_lock

_MAX_HISTORY = 256


class Autoscaler:
    def __init__(
        self,
        stats_fn: Callable[[], Dict[str, int]],
        scale_up_fn: Optional[Callable[[int], int]] = None,
        scale_down_fn: Optional[Callable[[int], int]] = None,
        *,
        blocked_threshold: int = 1,
        evals_per_node: int = 2,
        max_step: int = 8,
        cooldown_s: float = 3.0,
        drain_idle_ticks: int = 3,
    ) -> None:
        self.stats_fn = stats_fn
        self.scale_up_fn = scale_up_fn
        self.scale_down_fn = scale_down_fn
        self.blocked_threshold = max(1, int(blocked_threshold))
        self.evals_per_node = max(1, int(evals_per_node))
        self.max_step = max(1, int(max_step))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.drain_idle_ticks = max(1, int(drain_idle_ticks))

        self._lock = witness_lock("autoscaler.Autoscaler._lock")
        self._enabled = False
        self._last_action_t = float("-inf")
        self._idle_ticks = 0
        self.nodes_added = 0          # net nodes this autoscaler owns
        self.scale_ups = 0
        self.scale_downs = 0
        self.ticks = 0
        self.history: List[Dict[str, object]] = []

    # -- lifecycle -------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = bool(enabled)
            # fresh leadership starts from a clean cooldown: the first
            # pressured tick may act immediately
            self._last_action_t = float("-inf")
            self._idle_ticks = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- the loop --------------------------------------------------------

    def tick(self) -> Optional[Dict[str, object]]:
        """One policy evaluation; returns the action record if it acted.
        Scheduled as a leader task — exceptions from the callbacks
        propagate to the task wrapper's log-and-continue."""
        with self._lock:
            if not self._enabled:
                return None
            self.ticks += 1
        stats = self.stats_fn() or {}
        blocked = int(stats.get("total_blocked", 0) or 0)
        capacity.note_blocked_depth(blocked)
        metrics.set_gauge("nomad.autoscaler.blocked_depth", blocked)

        now = time.monotonic()
        action: Optional[Dict[str, object]] = None
        if blocked >= self.blocked_threshold:
            with self._lock:
                self._idle_ticks = 0
                in_cooldown = now - self._last_action_t < self.cooldown_s
            if not in_cooldown and self.scale_up_fn is not None:
                want = min(self.max_step,
                           -(-blocked // self.evals_per_node))
                added = int(self.scale_up_fn(want) or 0)
                if added > 0:
                    metrics.incr_counter("nomad.autoscaler.scale_up", added)
                    action = {"action": "scale_up", "blocked": blocked,
                              "requested": want, "nodes": added}
                    with self._lock:
                        self.nodes_added += added
                        self.scale_ups += 1
                        self._last_action_t = now
        else:
            with self._lock:
                self._idle_ticks += 1
                drainable = (
                    self._idle_ticks >= self.drain_idle_ticks
                    and self.nodes_added > 0
                    and now - self._last_action_t >= self.cooldown_s
                )
                step = min(self.max_step, self.nodes_added)
            if drainable and self.scale_down_fn is not None:
                removed = int(self.scale_down_fn(step) or 0)
                if removed > 0:
                    metrics.incr_counter(
                        "nomad.autoscaler.scale_down", removed)
                    action = {"action": "scale_down", "blocked": blocked,
                              "requested": step, "nodes": removed}
                    with self._lock:
                        self.nodes_added -= removed
                        self.scale_downs += 1
                        self._last_action_t = now
                        self._idle_ticks = 0
        if action is not None:
            with self._lock:
                self.history.append(action)
                del self.history[:-_MAX_HISTORY]
        metrics.set_gauge("nomad.autoscaler.nodes_added", self.nodes_added)
        return action

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "enabled": int(self._enabled),
                "ticks": self.ticks,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "nodes_added": self.nodes_added,
                "idle_ticks": self._idle_ticks,
            }
