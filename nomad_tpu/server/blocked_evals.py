"""Blocked-evaluations tracker: unblocks on capacity changes.

Semantics follow reference ``nomad/blocked_evals.go`` — evals that failed
placement wait keyed by computed node class (captured vs escaped), and are
re-enqueued when new capacity (node updates, alloc stops) appears. The
system-scheduler variant tracks per-node blocks (blocked_evals_system.go).

Unblock storms: one capacity burst (a wave of node registrations, a big
plan's stopped allocs) arrives as MANY triggers — per-class, per-node and
per-quota capacity changes, each of which would re-enqueue its interested
evals immediately. With ``coalesce_window_s > 0`` the triggers instead
stage their evals into a pending batch; a flush timer drains the batch as
ONE ``enqueue_all`` per window, deduped across triggers (an eval collected
by both a class and a node trigger re-enqueues once, carrying the highest
capacity index it witnessed). Each flush is capped at ``max_batch`` evals —
the remainder defers to the next window — so a 10K-eval storm reaches the
broker as bounded batches instead of one giant lock-hold + wakeup spike.
The flush path carries the ``unblock_enqueue`` chaos fire point: an
injected fault parks the batch and retries on a bounded-backoff timer
(degrade, never drop).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..chaos.injector import fire as chaos_fire
from ..structs.structs import EVAL_STATUS_PENDING, EVAL_TRIGGER_MAX_PLANS, Evaluation
from ..trace import capacity
from ..utils import metrics
from ..utils.lock_witness import witness_rlock
from ..utils.race_witness import tracked_dict

UNBLOCK_FAILED_INTERVAL = 60.0  # periodic retry of max-plan-failed evals

# retry backoff for a flush whose enqueue faulted (chaos or transient):
# bounded, so a flapping enqueue path degrades to spaced batches
FLUSH_RETRY_BACKOFF_S = 0.05


class BlockedEvals:
    def __init__(self, eval_broker, coalesce_window_s: float = 0.0,
                 max_batch: int = 512) -> None:
        self.eval_broker = eval_broker
        self.coalesce_window_s = max(0.0, float(coalesce_window_s))
        self.max_batch = max(1, int(max_batch))
        self._lock = witness_rlock("blocked_evals.BlockedEvals._lock")
        self.enabled = False

        # eval id -> eval
        self.captured: Dict[str, Evaluation] = {}
        # evals whose constraints escaped computed classes: unblock on any change
        self.escaped: Dict[str, Evaluation] = {}
        # eval id -> broker token held when the eval was blocked; a non-empty
        # token means the eval is still outstanding in the broker and must be
        # re-enqueued via the requeue-after-ack path (reference wrappedEval)
        self.tokens: Dict[str, str] = {}
        # (namespace, job id) -> eval id, to dedup per job
        self.job_blocks: Dict[Tuple[str, str], str] = {}
        # node id -> eval ids (system scheduler per-node blocks)
        self.system_blocks: Dict[str, Set[str]] = {}
        # class -> eval ids interested
        self.capacity_classes: Dict[str, Set[str]] = {}
        # evals blocked due to max plan attempts, retried periodically
        self.failed: Dict[str, Evaluation] = {}
        # capacity witnesses, to catch events racing the block window:
        # class -> index, node id -> index, quota -> index
        self.unblock_indexes: Dict[str, int] = {}
        self.node_unblock_indexes: Dict[str, int] = {}
        self.quota_unblock_indexes: Dict[str, int] = {}
        self.stats_blocked = 0

        # coalesced unblock staging: eval id -> (eval, token, index).
        # Triggers land evals here; the flush timer (or a synchronous
        # flush when coalesce_window_s == 0) drains it in bounded batches.
        self._pending: Dict[str, Tuple[Evaluation, str, int]] = tracked_dict(
            "blocked_evals.BlockedEvals._pending", {})
        self._flush_timer: Optional[threading.Timer] = None
        # cumulative storm counters (EmitStats parity + artifact fields)
        self.stats_unblocks = 0          # evals re-enqueued through flushes
        self.stats_unblock_batches = 0   # enqueue_all batches issued
        self.stats_dups_coalesced = 0    # cross-trigger dedup hits
        self.stats_unblock_deferred = 0  # flushes deferred (cap or fault)

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            prev = self.enabled
            self.enabled = enabled
        if prev and not enabled:
            self.flush()

    # ------------------------------------------------------------------

    def block(self, evaluation: Evaluation) -> None:
        """Track a blocked eval (no broker token — use ``reblock`` when the
        eval is still outstanding in the broker)."""
        with self._lock:
            self._process_block(evaluation, "")

    def reblock(self, evaluation: Evaluation, token: str) -> None:
        """Worker reblock of a still-outstanding eval (reference
        blocked_evals.go Reblock). On the leader the FSM eval-upsert hook has
        usually already captured the eval with an empty token; this records
        the delivery token on the tracked entry."""
        with self._lock:
            self._process_block(evaluation, token)

    def _process_block(self, evaluation: Evaluation, token: str) -> None:
        if not self.enabled:
            return
        if (
            evaluation.id in self.captured
            or evaluation.id in self.escaped
            or evaluation.id in self.failed
        ):
            # Already tracked (e.g. the FSM hook captured it before the
            # worker's reblock): record the non-empty token so the unblock
            # path can requeue-after-ack.
            if token:
                self.tokens[evaluation.id] = token
            return

        # Missed-unblock check (reference blocked_evals.go:202): if
        # relevant capacity appeared after the eval's snapshot, don't
        # block — re-enqueue right away.
        if self._missed_unblock(evaluation):
            new_eval = evaluation.copy()
            new_eval.status = EVAL_STATUS_PENDING
            self.eval_broker.enqueue_all({new_eval.id: (new_eval, token)})
            return

        # Dedup by job: keep the latest eval per job. Token is stored only
        # once the eval is actually tracked, so dropped evals don't leak
        # token entries.
        namespaced = (evaluation.namespace, evaluation.job_id)
        existing_id = self.job_blocks.get(namespaced)
        if existing_id is not None:
            existing = self.captured.get(existing_id) or self.escaped.get(existing_id)
            if existing is not None and existing.create_index >= evaluation.create_index:
                return
            self._remove(existing_id)
        self.job_blocks[namespaced] = evaluation.id
        if token:
            self.tokens[evaluation.id] = token

        if evaluation.triggered_by == EVAL_TRIGGER_MAX_PLANS:
            self.failed[evaluation.id] = evaluation
            return

        if evaluation.node_id:
            self.system_blocks.setdefault(evaluation.node_id, set()).add(evaluation.id)
            self.captured[evaluation.id] = evaluation
            return

        if evaluation.escaped_computed_class:
            self.escaped[evaluation.id] = evaluation
            return

        self.captured[evaluation.id] = evaluation
        # Index interest: eligible classes and unseen classes both unblock.
        for cls, eligible in (evaluation.class_eligibility or {}).items():
            if eligible:
                self.capacity_classes.setdefault(cls, set()).add(evaluation.id)

    def _missed_unblock(self, evaluation: Evaluation) -> bool:
        if evaluation.triggered_by == EVAL_TRIGGER_MAX_PLANS:
            return False
        snapshot = evaluation.snapshot_index
        if (
            evaluation.node_id
            and self.node_unblock_indexes.get(evaluation.node_id, 0) > snapshot
        ):
            return True
        if (
            evaluation.quota_limit_reached
            and self.quota_unblock_indexes.get(evaluation.quota_limit_reached, 0)
            > snapshot
        ):
            return True
        elig = evaluation.class_eligibility or {}
        for cls, index in self.unblock_indexes.items():
            if index <= snapshot:
                continue
            if evaluation.escaped_computed_class:
                return True
            # capacity in an eligible class, or a class the eval never saw
            if elig.get(cls, None) is not False:
                return True
        return False

    def _remove(self, eval_id: str) -> None:
        ev = self.captured.pop(eval_id, None) or self.escaped.pop(eval_id, None) \
            or self.failed.pop(eval_id, None)
        self.tokens.pop(eval_id, None)
        if ev is not None:
            self.job_blocks.pop((ev.namespace, ev.job_id), None)
        for ids in self.capacity_classes.values():
            ids.discard(eval_id)
        for ids in self.system_blocks.values():
            ids.discard(eval_id)

    def untrack(self, namespace: str, job_id: str) -> None:
        """Stop tracking blocked evals for a job (e.g. on deregister)."""
        with self._lock:
            eval_id = self.job_blocks.get((namespace, job_id))
            if eval_id:
                self._remove(eval_id)

    # ------------------------------------------------------------------

    def unblock(self, computed_class: str, index: int) -> None:
        """New capacity in a computed class: re-enqueue interested evals."""
        with self._lock:
            if not self.enabled:
                return
            self.unblock_indexes[computed_class] = index
            unblock: List[Evaluation] = []
            # escaped evals unblock on any change
            unblock.extend(self.escaped.values())
            self.escaped.clear()
            # captured evals: eligible for this class, or class unseen
            seen_ids = self.capacity_classes.pop(computed_class, set())
            for eval_id in list(self.captured):
                ev = self.captured[eval_id]
                elig = ev.class_eligibility or {}
                if eval_id in seen_ids or computed_class not in elig:
                    unblock.append(ev)
                    del self.captured[eval_id]
            self._enqueue(unblock, index)

    def unblock_node(self, node_id: str, index: int) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.node_unblock_indexes[node_id] = index
            ids = self.system_blocks.pop(node_id, set())
            unblock = [self.captured.pop(i) for i in ids if i in self.captured]
            self._enqueue(unblock, index)

    def unblock_failed(self) -> None:
        """Periodic retry of plan-conflict (max-plans) blocked evals."""
        with self._lock:
            if not self.enabled:
                return
            unblock = list(self.failed.values())
            self.failed.clear()
            self._enqueue(unblock, 0)

    def unblock_quota(self, quota: str, index: int) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.quota_unblock_indexes[quota] = index
            unblock = []
            for eval_id in list(self.captured):
                ev = self.captured[eval_id]
                if ev.quota_limit_reached == quota:
                    unblock.append(ev)
                    del self.captured[eval_id]
            self._enqueue(unblock, index)

    def _enqueue(self, evals: List[Evaluation], index: int) -> None:
        """Stage unblocked evals for a coalesced broker re-enqueue.

        Called under the lock by every trigger (class/node/quota/failed).
        An eval two triggers both collected inside one window dedups here
        and keeps the highest capacity index it witnessed (its refreshed
        snapshot_index must cover every capacity change that unblocked
        it, or the next block would spuriously look missed)."""
        for ev in evals:
            self.job_blocks.pop((ev.namespace, ev.job_id), None)
            token = self.tokens.pop(ev.id, "")
            ev_index = index
            prev = self._pending.get(ev.id)
            if prev is not None:
                self.stats_dups_coalesced += 1
                token = token or prev[1]
                ev_index = max(ev_index, prev[2])
            self._pending[ev.id] = (ev, token, ev_index)
        if not self._pending:
            return
        if self.coalesce_window_s <= 0:
            self._flush_pending_locked()
        else:
            self._schedule_flush_locked(self.coalesce_window_s)

    def _schedule_flush_locked(self, delay: float) -> None:
        if self._flush_timer is not None:
            return
        t = threading.Timer(delay, self._flush_timer_fire)
        t.daemon = True
        self._flush_timer = t
        t.start()

    def _flush_timer_fire(self) -> None:
        with self._lock:
            self._flush_timer = None
            if not self.enabled:
                self._pending.clear()
                return
            self._flush_pending_locked()

    def _flush_pending_locked(self) -> None:
        """Drain the staged batch into the broker, ``max_batch`` evals per
        ``enqueue_all``. In windowed mode the remainder past the cap defers
        to the next window tick (the spike bound); synchronous mode loops
        so callers that expect unblock-then-ready semantics keep them. An
        injected ``unblock_enqueue`` fault re-parks the batch and retries
        on a bounded-backoff timer."""
        while self._pending:
            chunk_ids = list(self._pending)[: self.max_batch]
            batch = {}
            for eid in chunk_ids:
                ev, token, index = self._pending[eid]
                new_eval = ev.copy()
                new_eval.status = EVAL_STATUS_PENDING
                new_eval.snapshot_index = index
                batch[eid] = (new_eval, token)
            try:
                # ChaosFault subclasses RuntimeError; production stays on
                # the fire-only import surface and catches the base
                chaos_fire("unblock_enqueue", batch=len(batch))
            except RuntimeError:
                self.stats_unblock_deferred += 1
                metrics.incr_counter("nomad.blocked_evals.unblock_deferred")
                self._schedule_flush_locked(
                    max(self.coalesce_window_s, FLUSH_RETRY_BACKOFF_S))
                return
            for eid in chunk_ids:
                del self._pending[eid]
            self.eval_broker.enqueue_all(batch)
            self.stats_unblock_batches += 1
            self.stats_unblocks += len(batch)
            capacity.record_batch(len(batch))
            capacity.mark_unblocked(batch)
            if self._pending and self.coalesce_window_s > 0:
                self.stats_unblock_deferred += 1
                metrics.incr_counter("nomad.blocked_evals.unblock_deferred")
                self._schedule_flush_locked(self.coalesce_window_s)
                return

    # ------------------------------------------------------------------

    def flush(self) -> None:
        with self._lock:
            self.captured.clear()
            self.escaped.clear()
            self.job_blocks.clear()
            self.system_blocks.clear()
            self.capacity_classes.clear()
            self.failed.clear()
            self.unblock_indexes.clear()
            self.node_unblock_indexes.clear()
            self.quota_unblock_indexes.clear()
            self.tokens.clear()
            # staged-but-unflushed unblocks die with leadership: the new
            # leader's eval restore re-enqueues anything non-terminal
            self._pending.clear()
            timer = self._flush_timer
            self._flush_timer = None
        if timer is not None:
            timer.cancel()

    def stats(self) -> Dict[str, int]:
        """EmitStats parity (blocked_evals.go:774): depth gauges plus the
        storm counters the capacity-pressure SLO gate reads."""
        with self._lock:
            return {
                "total_blocked": len(self.captured) + len(self.escaped),
                "total_escaped": len(self.escaped),
                "total_failed": len(self.failed),
                "total_captured": len(self.captured),
                "total_system_blocked": sum(
                    len(ids) for ids in self.system_blocks.values()
                ),
                "pending_unblocks": len(self._pending),
                "unblocks_total": self.stats_unblocks,
                "unblock_batches": self.stats_unblock_batches,
                "unblock_dups_coalesced": self.stats_dups_coalesced,
                "unblock_deferred": self.stats_unblock_deferred,
            }
