"""Blocked-evaluations tracker: unblocks on capacity changes.

Semantics follow reference ``nomad/blocked_evals.go`` — evals that failed
placement wait keyed by computed node class (captured vs escaped), and are
re-enqueued when new capacity (node updates, alloc stops) appears. The
system-scheduler variant tracks per-node blocks (blocked_evals_system.go).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..structs.structs import EVAL_STATUS_PENDING, EVAL_TRIGGER_MAX_PLANS, Evaluation

UNBLOCK_FAILED_INTERVAL = 60.0  # periodic retry of max-plan-failed evals


class BlockedEvals:
    def __init__(self, eval_broker) -> None:
        self.eval_broker = eval_broker
        self._lock = threading.RLock()
        self.enabled = False

        # eval id -> eval
        self.captured: Dict[str, Evaluation] = {}
        # evals whose constraints escaped computed classes: unblock on any change
        self.escaped: Dict[str, Evaluation] = {}
        # eval id -> broker token held when the eval was blocked; a non-empty
        # token means the eval is still outstanding in the broker and must be
        # re-enqueued via the requeue-after-ack path (reference wrappedEval)
        self.tokens: Dict[str, str] = {}
        # (namespace, job id) -> eval id, to dedup per job
        self.job_blocks: Dict[Tuple[str, str], str] = {}
        # node id -> eval ids (system scheduler per-node blocks)
        self.system_blocks: Dict[str, Set[str]] = {}
        # class -> eval ids interested
        self.capacity_classes: Dict[str, Set[str]] = {}
        # evals blocked due to max plan attempts, retried periodically
        self.failed: Dict[str, Evaluation] = {}
        # capacity witnesses, to catch events racing the block window:
        # class -> index, node id -> index, quota -> index
        self.unblock_indexes: Dict[str, int] = {}
        self.node_unblock_indexes: Dict[str, int] = {}
        self.quota_unblock_indexes: Dict[str, int] = {}
        self.stats_blocked = 0

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            prev = self.enabled
            self.enabled = enabled
        if prev and not enabled:
            self.flush()

    # ------------------------------------------------------------------

    def block(self, evaluation: Evaluation) -> None:
        """Track a blocked eval (no broker token — use ``reblock`` when the
        eval is still outstanding in the broker)."""
        with self._lock:
            self._process_block(evaluation, "")

    def reblock(self, evaluation: Evaluation, token: str) -> None:
        """Worker reblock of a still-outstanding eval (reference
        blocked_evals.go Reblock). On the leader the FSM eval-upsert hook has
        usually already captured the eval with an empty token; this records
        the delivery token on the tracked entry."""
        with self._lock:
            self._process_block(evaluation, token)

    def _process_block(self, evaluation: Evaluation, token: str) -> None:
        if not self.enabled:
            return
        if (
            evaluation.id in self.captured
            or evaluation.id in self.escaped
            or evaluation.id in self.failed
        ):
            # Already tracked (e.g. the FSM hook captured it before the
            # worker's reblock): record the non-empty token so the unblock
            # path can requeue-after-ack.
            if token:
                self.tokens[evaluation.id] = token
            return

        # Missed-unblock check (reference blocked_evals.go:202): if
        # relevant capacity appeared after the eval's snapshot, don't
        # block — re-enqueue right away.
        if self._missed_unblock(evaluation):
            new_eval = evaluation.copy()
            new_eval.status = EVAL_STATUS_PENDING
            self.eval_broker.enqueue_all({new_eval.id: (new_eval, token)})
            return

        # Dedup by job: keep the latest eval per job. Token is stored only
        # once the eval is actually tracked, so dropped evals don't leak
        # token entries.
        namespaced = (evaluation.namespace, evaluation.job_id)
        existing_id = self.job_blocks.get(namespaced)
        if existing_id is not None:
            existing = self.captured.get(existing_id) or self.escaped.get(existing_id)
            if existing is not None and existing.create_index >= evaluation.create_index:
                return
            self._remove(existing_id)
        self.job_blocks[namespaced] = evaluation.id
        if token:
            self.tokens[evaluation.id] = token

        if evaluation.triggered_by == EVAL_TRIGGER_MAX_PLANS:
            self.failed[evaluation.id] = evaluation
            return

        if evaluation.node_id:
            self.system_blocks.setdefault(evaluation.node_id, set()).add(evaluation.id)
            self.captured[evaluation.id] = evaluation
            return

        if evaluation.escaped_computed_class:
            self.escaped[evaluation.id] = evaluation
            return

        self.captured[evaluation.id] = evaluation
        # Index interest: eligible classes and unseen classes both unblock.
        for cls, eligible in (evaluation.class_eligibility or {}).items():
            if eligible:
                self.capacity_classes.setdefault(cls, set()).add(evaluation.id)

    def _missed_unblock(self, evaluation: Evaluation) -> bool:
        if evaluation.triggered_by == EVAL_TRIGGER_MAX_PLANS:
            return False
        snapshot = evaluation.snapshot_index
        if (
            evaluation.node_id
            and self.node_unblock_indexes.get(evaluation.node_id, 0) > snapshot
        ):
            return True
        if (
            evaluation.quota_limit_reached
            and self.quota_unblock_indexes.get(evaluation.quota_limit_reached, 0)
            > snapshot
        ):
            return True
        elig = evaluation.class_eligibility or {}
        for cls, index in self.unblock_indexes.items():
            if index <= snapshot:
                continue
            if evaluation.escaped_computed_class:
                return True
            # capacity in an eligible class, or a class the eval never saw
            if elig.get(cls, None) is not False:
                return True
        return False

    def _remove(self, eval_id: str) -> None:
        ev = self.captured.pop(eval_id, None) or self.escaped.pop(eval_id, None) \
            or self.failed.pop(eval_id, None)
        self.tokens.pop(eval_id, None)
        if ev is not None:
            self.job_blocks.pop((ev.namespace, ev.job_id), None)
        for ids in self.capacity_classes.values():
            ids.discard(eval_id)
        for ids in self.system_blocks.values():
            ids.discard(eval_id)

    def untrack(self, namespace: str, job_id: str) -> None:
        """Stop tracking blocked evals for a job (e.g. on deregister)."""
        with self._lock:
            eval_id = self.job_blocks.get((namespace, job_id))
            if eval_id:
                self._remove(eval_id)

    # ------------------------------------------------------------------

    def unblock(self, computed_class: str, index: int) -> None:
        """New capacity in a computed class: re-enqueue interested evals."""
        with self._lock:
            if not self.enabled:
                return
            self.unblock_indexes[computed_class] = index
            unblock: List[Evaluation] = []
            # escaped evals unblock on any change
            unblock.extend(self.escaped.values())
            self.escaped.clear()
            # captured evals: eligible for this class, or class unseen
            seen_ids = self.capacity_classes.pop(computed_class, set())
            for eval_id in list(self.captured):
                ev = self.captured[eval_id]
                elig = ev.class_eligibility or {}
                if eval_id in seen_ids or computed_class not in elig:
                    unblock.append(ev)
                    del self.captured[eval_id]
            self._enqueue(unblock, index)

    def unblock_node(self, node_id: str, index: int) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.node_unblock_indexes[node_id] = index
            ids = self.system_blocks.pop(node_id, set())
            unblock = [self.captured.pop(i) for i in ids if i in self.captured]
            self._enqueue(unblock, index)

    def unblock_failed(self) -> None:
        """Periodic retry of plan-conflict (max-plans) blocked evals."""
        with self._lock:
            if not self.enabled:
                return
            unblock = list(self.failed.values())
            self.failed.clear()
            self._enqueue(unblock, 0)

    def unblock_quota(self, quota: str, index: int) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.quota_unblock_indexes[quota] = index
            unblock = []
            for eval_id in list(self.captured):
                ev = self.captured[eval_id]
                if ev.quota_limit_reached == quota:
                    unblock.append(ev)
                    del self.captured[eval_id]
            self._enqueue(unblock, index)

    def _enqueue(self, evals: List[Evaluation], index: int) -> None:
        batch = {}
        for ev in evals:
            self.job_blocks.pop((ev.namespace, ev.job_id), None)
            token = self.tokens.pop(ev.id, "")
            new_eval = ev.copy()
            new_eval.status = EVAL_STATUS_PENDING
            new_eval.snapshot_index = index
            batch[new_eval.id] = (new_eval, token)
        if batch:
            self.eval_broker.enqueue_all(batch)

    # ------------------------------------------------------------------

    def flush(self) -> None:
        with self._lock:
            self.captured.clear()
            self.escaped.clear()
            self.job_blocks.clear()
            self.system_blocks.clear()
            self.capacity_classes.clear()
            self.failed.clear()
            self.unblock_indexes.clear()
            self.node_unblock_indexes.clear()
            self.quota_unblock_indexes.clear()
            self.tokens.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "total_blocked": len(self.captured) + len(self.escaped),
                "total_escaped": len(self.escaped),
                "total_failed": len(self.failed),
            }
