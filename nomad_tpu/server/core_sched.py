"""Core scheduler: internal GC jobs (reference ``nomad/core_sched.go``).

Thresholds are ages; the server's TimeTable translates them to raft-index
cutoffs (objects with modify_index below the cutoff are old enough).
"""
from __future__ import annotations

import logging
import time
from typing import List

from ..structs.structs import (
    CORE_JOB_DEPLOYMENT_GC,
    CORE_JOB_EVAL_GC,
    CORE_JOB_FORCE_GC,
    CORE_JOB_JOB_GC,
    CORE_JOB_NODE_GC,
    JOB_STATUS_DEAD,
    Evaluation,
)
from .fsm import DEPLOYMENT_DELETE, EVAL_DELETE, JOB_DEREGISTER, NODE_DEREGISTER

EVAL_GC_THRESHOLD_NS = 3600 * 10**9  # 1h
JOB_GC_THRESHOLD_NS = 4 * 3600 * 10**9
NODE_GC_THRESHOLD_NS = 24 * 3600 * 10**9
DEPLOYMENT_GC_THRESHOLD_NS = 3600 * 10**9


class CoreScheduler:
    def __init__(self, server, snapshot) -> None:
        self.server = server
        self.snapshot = snapshot
        self.logger = logging.getLogger("nomad_tpu.core_sched")

    def process(self, evaluation: Evaluation) -> None:
        job_id = evaluation.job_id
        force = job_id.startswith(CORE_JOB_FORCE_GC)
        if job_id.startswith(CORE_JOB_EVAL_GC) or force:
            self._eval_gc(force)
        if job_id.startswith(CORE_JOB_JOB_GC) or force:
            self._job_gc(force)
        if job_id.startswith(CORE_JOB_NODE_GC) or force:
            self._node_gc(force)
        if job_id.startswith(CORE_JOB_DEPLOYMENT_GC) or force:
            self._deployment_gc(force)

    def _cutoff_index(self, threshold_ns: int, force: bool) -> int:
        """Objects with modify_index <= cutoff are older than the threshold."""
        if force:
            return self.snapshot.latest_index
        return self.server.timetable.nearest_index(time.time_ns() - threshold_ns)

    def _eval_gc(self, force: bool) -> None:
        cutoff = self._cutoff_index(EVAL_GC_THRESHOLD_NS, force)
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for ev in self.snapshot.evals():
            if not ev.terminal_status() or ev.modify_index > cutoff:
                continue
            allocs = self.snapshot.allocs_by_eval(ev.id)
            if any(
                not a.terminal_status() or a.modify_index > cutoff for a in allocs
            ):
                continue
            gc_evals.append(ev.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals or gc_allocs:
            self.server.raft_apply(EVAL_DELETE, (gc_evals, gc_allocs))

    def _job_gc(self, force: bool) -> None:
        cutoff = self._cutoff_index(JOB_GC_THRESHOLD_NS, force)
        for job in self.snapshot.jobs():
            if not (job.stopped() or job.status == JOB_STATUS_DEAD):
                continue
            if job.is_periodic() or job.is_parameterized():
                continue
            if job.modify_index > cutoff:
                continue
            allocs = self.snapshot.allocs_by_job(job.namespace, job.id, True)
            if any(not a.terminal_status() for a in allocs):
                continue
            evals = self.snapshot.evals_by_job(job.namespace, job.id)
            if any(not e.terminal_status() for e in evals):
                continue
            self.server.raft_apply(JOB_DEREGISTER, (job.namespace, job.id, True))

    def _node_gc(self, force: bool) -> None:
        cutoff = self._cutoff_index(NODE_GC_THRESHOLD_NS, force)
        for node in self.snapshot.nodes():
            if not node.terminal_status() or node.modify_index > cutoff:
                continue
            allocs = self.snapshot.allocs_by_node(node.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            self.server.raft_apply(NODE_DEREGISTER, node.id)

    def _deployment_gc(self, force: bool) -> None:
        cutoff = self._cutoff_index(DEPLOYMENT_GC_THRESHOLD_NS, force)
        gc: List[str] = []
        for d in self.snapshot.deployments():
            if d.active() or d.modify_index > cutoff:
                continue
            gc.append(d.id)
        if gc:
            self.server.raft_apply(DEPLOYMENT_DELETE, gc)
