"""Deployment watcher: drives rolling/canary deployments to completion.

Fills the role of reference ``nomad/deploymentwatcher/`` (deployments_watcher.go:60
Watcher, deployment_watcher.go per-deployment goroutine, batcher.go). Instead
of one goroutine per deployment, one watcher thread wakes on every state-store
index bump (blocking query, state_store.go:188 analog) and evaluates every
active deployment in a single pass — cheaper at C1M deployment counts and
naturally batched, which is the same reshaping applied to the scheduler
(per-node iterators → one vectorized pass).

Per-deployment logic reproduced from the reference:
- cancel when the job is stopped/removed or a newer job version supersedes it
  (deployment_watcher.go getDeploymentStatusUpdate / watchJobVersion)
- fail on unhealthy allocs, with optional auto-revert to the latest stable
  job version (deployment_watcher.go:FailDeployment, handleAllocUpdate)
- fail when a group misses its progress deadline (watchDeadline)
- auto-promote once every desired canary is placed and healthy
  (deployments_watcher.go autoPromoteDeployments)
- mark successful + flag the job version stable when all groups are done
  (deployment_watcher.go watchAllocs → setDeploymentStatus)

State mutations ride raft ops (DEPLOYMENT_STATUS_UPDATE / DEPLOYMENT_PROMOTE /
DEPLOYMENT_ALLOC_HEALTH / JOB_STABILITY) so followers replay identically, and
every transition emits an eval (EVAL_TRIGGER_DEPLOYMENT_WATCHER) so the
scheduler reacts — same protocol as the reference's shims
(deployment_watcher_shims.go).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from ..structs.structs import (
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    Deployment,
    DeploymentStatusUpdate,
    Evaluation,
    Job,
)
from ..utils.lock_witness import witness_lock

# status descriptions (reference structs.go DeploymentStatusDescription*)
DESC_RUNNING = "Deployment is running"
DESC_PAUSED = "Deployment is paused"
DESC_SUCCESSFUL = "Deployment completed successfully"
DESC_STOPPED_JOB = "Cancelled because job is stopped"
DESC_NEWER_JOB = "Cancelled due to newer version of job"
DESC_FAILED_ALLOCS = "Failed due to unhealthy allocations"
DESC_FAILED_BY_USER = "Deployment marked as failed"
DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_AUTO_PROMOTE = "Deployment promoted automatically"


def _rollback_suffix(desc: str, version: int) -> str:
    return f"{desc} - rolling back to job version {version}"


class DeploymentsWatcher:
    """Leader-only monitor of active deployments."""

    def __init__(self, server, poll_interval: float = 1.0) -> None:
        self.server = server
        self.poll_interval = poll_interval
        self.logger = logging.getLogger("nomad_tpu.deploymentwatcher")
        self._enabled = False
        self._thread: Optional[threading.Thread] = None
        self._generation = 0
        self._lock = witness_lock("deploymentwatcher.DeploymentsWatcher._lock")
        # deployment id → last observed healthy-alloc total, for detecting
        # mid-rollout health transitions that must kick the scheduler
        self._last_healthy: dict = {}

    # -- lifecycle -------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            self._generation += 1
            gen = self._generation
        if enabled:
            t = threading.Thread(
                target=self._run, args=(gen,), name="deploymentwatcher", daemon=True
            )
            self._thread = t
            t.start()

    def _run(self, gen: int) -> None:
        state = self.server.fsm.state
        last_index = 0
        while True:
            with self._lock:
                if not self._enabled or self._generation != gen:
                    return
            try:
                self.tick()
            except Exception:  # noqa: BLE001
                self.logger.exception("deployment watcher tick failed")
            # Wake on any state change (allocs/health land as index bumps) or
            # after poll_interval to re-check wall-clock progress deadlines.
            _, last_index = state.blocking_query(
                lambda s: None, last_index, timeout=self.poll_interval
            )

    # -- one evaluation pass over all active deployments -----------------

    def tick(self, now_ns: Optional[int] = None) -> None:
        now_ns = now_ns if now_ns is not None else time.time_ns()
        state = self.server.fsm.state
        active_ids = set()
        for d in state.deployments():
            if not d.active():
                continue
            active_ids.add(d.id)
            try:
                self._check_deployment(state, d, now_ns)
            except Exception:  # noqa: BLE001
                self.logger.exception("deployment %s check failed", d.id)
        # failed/cancelled deployments must not leak health counters
        for did in list(self._last_healthy):
            if did not in active_ids:
                self._last_healthy.pop(did, None)

    def _check_deployment(self, state, d: Deployment, now_ns: int) -> None:
        job = state.job_by_id(d.namespace, d.job_id)
        # cancelled: job stopped/removed or superseded by a newer version
        if job is None or job.stopped():
            self._update_status(d, DEPLOYMENT_STATUS_CANCELLED, DESC_STOPPED_JOB)
            return
        if job.version != d.job_version:
            self._update_status(d, DEPLOYMENT_STATUS_CANCELLED, DESC_NEWER_JOB)
            return
        if d.status == DEPLOYMENT_STATUS_PAUSED:
            return

        # failed: unhealthy allocation appeared
        if any(ds.unhealthy_allocs > 0 for ds in d.task_groups.values()):
            self._fail(d, DESC_FAILED_ALLOCS)
            return

        # failed: a group missed its progress deadline
        for ds in d.task_groups.values():
            done = ds.healthy_allocs >= ds.desired_total and (
                ds.desired_canaries == 0 or ds.promoted
            )
            if (
                not done
                and ds.require_progress_by_ns > 0
                and now_ns > ds.require_progress_by_ns
            ):
                self._fail(d, DESC_PROGRESS_DEADLINE)
                return

        # auto-promote: every canary group opted in, all canaries healthy
        if d.requires_promotion():
            canary_groups = [
                ds for ds in d.task_groups.values() if ds.desired_canaries > 0
            ]
            if all(ds.auto_promote for ds in canary_groups) and all(
                len(ds.placed_canaries) >= ds.desired_canaries
                and ds.healthy_allocs >= ds.desired_canaries
                for ds in canary_groups
            ):
                self.promote(d.id, description=DESC_AUTO_PROMOTE)
            return  # promotion (manual or auto) gates completion

        # success: every group fully healthy and promoted where required
        if d.task_groups and all(
            ds.healthy_allocs >= ds.desired_total for ds in d.task_groups.values()
        ):
            self._last_healthy.pop(d.id, None)
            self._update_status(d, DEPLOYMENT_STATUS_SUCCESSFUL, DESC_SUCCESSFUL)
            self.server.raft_apply(
                "job-stability", (d.namespace, d.job_id, d.job_version, True)
            )
            return

        # progress: an alloc newly became healthy mid-rollout — kick the
        # scheduler so the next max_parallel batch places (reference
        # deployment_watcher.go createBatchedUpdateEvaluation on alloc
        # health transitions; without this a rolling update stalls after
        # its first batch)
        total_healthy = sum(ds.healthy_allocs for ds in d.task_groups.values())
        # default 0, not None: a deployment first observed with healthy
        # allocs already recorded (health landed before our first tick)
        # must still kick the scheduler, or the rollout stalls forever
        prev = self._last_healthy.get(d.id, 0)
        self._last_healthy[d.id] = total_healthy
        if total_healthy > prev:
            ev = self._make_eval(d, job)
            self.server.raft_apply("eval-update", [ev])

    # -- transitions -----------------------------------------------------

    def _make_eval(self, d: Deployment, job: Optional[Job] = None) -> Evaluation:
        ev = Evaluation(
            namespace=d.namespace,
            priority=job.priority if job is not None else 50,
            type=job.type if job is not None else "service",
            triggered_by=EVAL_TRIGGER_DEPLOYMENT_WATCHER,
            job_id=d.job_id,
            deployment_id=d.id,
            status=EVAL_STATUS_PENDING,
        )
        ev.update_modify_time()
        return ev

    def _update_status(
        self, d: Deployment, status: str, description: str, job: Optional[Job] = None
    ) -> None:
        update = DeploymentStatusUpdate(
            deployment_id=d.id, status=status, status_description=description
        )
        state_job = self.server.fsm.state.job_by_id(d.namespace, d.job_id)
        ev = self._make_eval(d, state_job) if status != DEPLOYMENT_STATUS_CANCELLED else None
        self.server.raft_apply("deployment-status-update", (update, job, ev))
        self.logger.info("deployment %s -> %s (%s)", d.id[:8], status, description)

    def _latest_stable_job(self, d: Deployment) -> Optional[Job]:
        """Newest job version flagged stable, below the deployment's version
        (reference deployment_watcher.go latestStableJob)."""
        versions = self.server.fsm.state.job_versions.get((d.namespace, d.job_id), [])
        stable = [j for j in versions if j.stable and j.version < d.job_version]
        if not stable:
            return None
        return max(stable, key=lambda j: j.version).copy()

    def _fail(self, d: Deployment, description: str) -> None:
        rollback = None
        if any(ds.auto_revert for ds in d.task_groups.values()):
            stable = self._latest_stable_job(d)
            if stable is not None:
                description = _rollback_suffix(description, stable.version)
                rollback = stable  # re-upsert bumps it to a fresh version
        self._update_status(d, DEPLOYMENT_STATUS_FAILED, description, job=rollback)

    # -- endpoint surface (Deployment.* RPCs) ----------------------------

    def promote(
        self,
        deployment_id: str,
        groups: Optional[List[str]] = None,
        description: str = DESC_RUNNING,
    ) -> None:
        """Deployment.Promote (deployments_watcher.go PromoteDeployment)."""
        state = self.server.fsm.state
        d = state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal ({d.status})")
        if not d.requires_promotion():
            raise ValueError(f"deployment {deployment_id} has nothing to promote")
        job = state.job_by_id(d.namespace, d.job_id)
        ev = self._make_eval(d, job)
        self.server.raft_apply(
            "deployment-promote", (deployment_id, groups, description, ev)
        )

    def pause(self, deployment_id: str, pause: bool) -> None:
        """Deployment.Pause (deployments_watcher.go PauseDeployment)."""
        d = self.server.fsm.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal ({d.status})")
        if pause:
            update = DeploymentStatusUpdate(
                deployment_id=d.id,
                status=DEPLOYMENT_STATUS_PAUSED,
                status_description=DESC_PAUSED,
            )
            self.server.raft_apply("deployment-status-update", (update, None, None))
        else:
            self._update_status(d, DEPLOYMENT_STATUS_RUNNING, DESC_RUNNING)

    def fail(self, deployment_id: str) -> None:
        """Deployment.Fail (deployments_watcher.go FailDeployment)."""
        state = self.server.fsm.state
        d = state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal ({d.status})")
        self._fail(d, DESC_FAILED_BY_USER)

    def set_alloc_health(
        self,
        deployment_id: str,
        healthy: Optional[List[str]] = None,
        unhealthy: Optional[List[str]] = None,
    ) -> None:
        """Deployment.SetAllocHealth — explicit health reports (the
        reference batches these per 250ms, batcher.go; raft op is cheap
        enough here to apply directly)."""
        state = self.server.fsm.state
        d = state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        self.server.raft_apply(
            "deployment-alloc-health",
            (deployment_id, healthy or [], unhealthy or [], time.time_ns(), None, None),
        )
