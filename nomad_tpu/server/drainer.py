"""Node drainer: migrates allocations off draining nodes.

Fills the role of reference ``nomad/drainer/`` (drainer.go:130 NodeDrainer,
watch_jobs.go per-job drain batching, watch_nodes.go:19, drain_heap.go
deadline heap). Same reshaping as the deployment watcher: instead of
per-node/per-job goroutines plus a deadline heap, one thread wakes on every
state bump (and on a short interval for wall-clock deadlines) and computes
every draining node's next action in a single pass.

Reference semantics reproduced:
- service allocs drain in batches of the task group's ``migrate.max_parallel``,
  waiting for replacements to come up before draining more
  (watch_jobs.go handleTaskGroup); migration rides
  ``DesiredTransition{migrate=True}`` raft ops + an eval, and the generic
  reconciler does the actual stop+place (reconcile_util filter_by_tainted).
- batch allocs are left to finish until the drain deadline
  (drainer.go: batch jobs on draining nodes cut off only at deadline).
- system allocs drain only after everything else is off the node, unless
  ``ignore_system_jobs`` (drainer.go handleDeadlinedNodes / system handling).
- at ``force_deadline_ns`` everything remaining is migrated at once
  (drainer.go:243 handleDeadlinedNodes).
- when nothing drainable remains the drain is marked complete with the node
  left ineligible (watch_nodes.go deregister + batch drain-complete raft op).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs.structs import (
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_NODE_DRAIN,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    Allocation,
    DesiredTransition,
    Evaluation,
    Node,
)
from ..utils.lock_witness import witness_lock


class NodeDrainer:
    """Leader-only drain driver."""

    def __init__(self, server, poll_interval: float = 1.0) -> None:
        self.server = server
        self.poll_interval = poll_interval
        self.logger = logging.getLogger("nomad_tpu.drainer")
        self._enabled = False
        self._thread: Optional[threading.Thread] = None
        self._generation = 0
        self._lock = witness_lock("drainer.NodeDrainer._lock")

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            self._generation += 1
            gen = self._generation
        if enabled:
            t = threading.Thread(target=self._run, args=(gen,), name="drainer", daemon=True)
            self._thread = t
            t.start()

    def _run(self, gen: int) -> None:
        state = self.server.fsm.state
        last_index = 0
        while True:
            with self._lock:
                if not self._enabled or self._generation != gen:
                    return
            try:
                self.tick()
            except Exception:  # noqa: BLE001
                self.logger.exception("drainer tick failed")
            _, last_index = state.blocking_query(
                lambda s: None, last_index, timeout=self.poll_interval
            )

    # ------------------------------------------------------------------

    def tick(self, now_ns: Optional[int] = None) -> None:
        now_ns = now_ns if now_ns is not None else time.time_ns()
        state = self.server.fsm.state
        draining = [n for n in state.nodes() if n.drain and n.drain_strategy is not None]
        if not draining:
            return

        to_migrate: List[Allocation] = []
        drain_complete: Dict[str, Tuple[None, bool]] = {}
        # service allocs pool across ALL draining nodes, so max_parallel is
        # a per-task-group budget, not per-node (two draining nodes holding
        # the same group must share one batch)
        service_pool: Dict[Tuple[str, str, str], List[Allocation]] = {}
        for node in draining:
            migrate, service, complete = self._handle_node(state, node, now_ns)
            to_migrate.extend(migrate)
            for a in service:
                service_pool.setdefault((a.namespace, a.job_id, a.task_group), []).append(a)
            if complete:
                drain_complete[node.id] = (None, False)  # stay ineligible

        # force-marked allocs aren't in state yet this tick; the batch
        # calculation must still see them as unavailable
        force_marked_ids = {a.id for a in to_migrate}
        for (namespace, job_id, tg_name), group in service_pool.items():
            to_migrate.extend(
                self._drain_batch_for_group(
                    state, namespace, job_id, tg_name, group, force_marked_ids
                )
            )

        if to_migrate:
            self._apply_migrations(state, to_migrate)
        if drain_complete:
            self.server.raft_apply("batch-node-update-drain", drain_complete)
            for node_id in drain_complete:
                self.logger.info("node %s drain complete", node_id[:8])

    def _handle_node(
        self, state, node: Node, now_ns: int
    ) -> Tuple[List[Allocation], List[Allocation], bool]:
        """Returns (allocs to migrate-mark now, service allocs for the
        cross-node batching pool, drain complete?)."""
        strategy = node.drain_strategy
        allocs = [
            a
            for a in state.allocs_by_node(node.id)
            if not a.terminal_status() and a.desired_status == ALLOC_DESIRED_RUN
        ]
        remaining = [a for a in allocs if not a.desired_transition.should_migrate()]

        def job_type(alloc: Allocation) -> str:
            job = alloc.job or state.job_by_id(alloc.namespace, alloc.job_id)
            return job.type if job is not None else JOB_TYPE_SERVICE

        system = [a for a in remaining if job_type(a) == JOB_TYPE_SYSTEM]
        batch = [a for a in remaining if job_type(a) == JOB_TYPE_BATCH]
        service = [a for a in remaining if job_type(a) == JOB_TYPE_SERVICE]

        forced = strategy.deadline_passed(now_ns)
        if forced:
            # deadline: everything left goes at once
            marked = service + batch + ([] if strategy.ignore_system_jobs else system)
            drainable_left = [a for a in allocs if a.desired_transition.should_migrate()]
            return marked, [], not marked and not drainable_left

        # pre-deadline: service allocs go to the shared batching pool; batch
        # allocs run to completion; system waits for the rest
        marked: List[Allocation] = []
        others_active = bool(service or batch) or any(
            a.desired_transition.should_migrate() and job_type(a) != JOB_TYPE_SYSTEM
            for a in allocs
        )
        if not others_active and system and not strategy.ignore_system_jobs:
            marked.extend(system)

        in_flight = [a for a in allocs if a.desired_transition.should_migrate()]
        ignored_system = system if strategy.ignore_system_jobs else []
        complete = (
            not marked
            and not in_flight
            and not service
            and not batch
            and len(system) == len(ignored_system)
        )
        return marked, service, complete

    def _drain_batch_for_group(
        self,
        state,
        namespace: str,
        job_id: str,
        tg_name: str,
        on_node: List[Allocation],
        force_marked_ids,
    ) -> List[Allocation]:
        """Pick the next drain batch for one task group: keep at least
        ``count - max_parallel`` healthy allocs at all times (reference
        watch_jobs.go handleTaskGroup threshold count). ``force_marked_ids``
        are allocs another node's passed deadline marked this same tick."""
        job = on_node[0].job or state.job_by_id(namespace, job_id)
        tg = job.lookup_task_group(tg_name) if job is not None else None
        if tg is None:
            return on_node  # job gone: nothing to protect
        max_parallel = tg.migrate.max_parallel if tg.migrate is not None else 1

        healthy = 0
        for a in state.allocs_by_job(namespace, job_id, False):
            if a.task_group != tg_name or a.terminal_status():
                continue
            if a.desired_transition.should_migrate() or a.id in force_marked_ids:
                continue  # scheduled to stop
            if a.client_status != ALLOC_CLIENT_RUNNING:
                continue  # replacement still coming up
            if a.deployment_status is not None and a.deployment_status.is_unhealthy():
                continue
            healthy += 1

        threshold = tg.count - max_parallel
        num_to_drain = healthy - threshold
        if num_to_drain <= 0:
            return []
        return on_node[:num_to_drain]

    def _apply_migrations(self, state, allocs: List[Allocation]) -> None:
        """Raft-apply migrate transitions + one drain eval per job
        (reference drainer.go:357 batchDrainAllocs / drainer_util.go)."""
        transitions = {a.id: DesiredTransition(migrate=True) for a in allocs}
        evals: List[Evaluation] = []
        seen = set()
        for a in allocs:
            key = (a.namespace, a.job_id)
            if key in seen:
                continue
            seen.add(key)
            job = a.job or state.job_by_id(a.namespace, a.job_id)
            ev = Evaluation(
                namespace=a.namespace,
                priority=job.priority if job is not None else 50,
                type=job.type if job is not None else JOB_TYPE_SERVICE,
                triggered_by=EVAL_TRIGGER_NODE_DRAIN,
                job_id=a.job_id,
                status=EVAL_STATUS_PENDING,
            )
            ev.update_modify_time()
            evals.append(ev)
        self.server.raft_apply(
            "alloc-update-desired-transition", (transitions, evals)
        )
