"""Evaluation broker: leader-only priority queue with at-least-once delivery.

Semantics follow reference ``nomad/eval_broker.go`` — per-scheduler priority
heaps, per-job serialization, Nack timers with compounding re-enqueue delay,
a delivery limit feeding the ``_failed`` queue, and a delay heap for
``wait_until`` evals.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..chaos.injector import fire as chaos_fire
from ..structs.structs import Evaluation, generate_uuid
from ..trace import capacity as _capacity
from ..trace import lifecycle as _trace
from ..utils.lock_witness import witness_rlock
from ..utils.race_witness import tracked_dict

FAILED_QUEUE = "_failed"

DEFAULT_NACK_TIMEOUT = 60.0
DEFAULT_DELIVERY_LIMIT = 3
DEFAULT_INITIAL_NACK_DELAY = 1.0
DEFAULT_SUBSEQUENT_NACK_DELAY = 20.0


class NotOutstandingError(Exception):
    pass


class TokenMismatchError(Exception):
    pass


class _PendingHeap:
    """Priority heap: higher priority first, FIFO within a priority."""

    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, Evaluation]] = []
        self._counter = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._heap, (-ev.priority, next(self._counter), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[2]

    def peek(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return self._heap[0][2]

    def __len__(self) -> int:
        return len(self._heap)


class _Unack:
    __slots__ = ("eval", "token", "nack_timer")

    def __init__(self, eval: Evaluation, token: str, nack_timer: threading.Timer):
        self.eval = eval
        self.token = token
        self.nack_timer = nack_timer


class EvalBroker:
    def __init__(
        self,
        nack_timeout: float = DEFAULT_NACK_TIMEOUT,
        delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
        initial_nack_delay: float = DEFAULT_INITIAL_NACK_DELAY,
        subsequent_nack_delay: float = DEFAULT_SUBSEQUENT_NACK_DELAY,
    ) -> None:
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        self.initial_nack_delay = initial_nack_delay
        self.subsequent_nack_delay = subsequent_nack_delay

        self._lock = witness_rlock("eval_broker.EvalBroker._lock")
        self._cond = threading.Condition(self._lock)
        self.enabled = False

        # eval id -> delivery attempts
        self.evals: Dict[str, int] = tracked_dict(
            "eval_broker.EvalBroker.evals", {})
        # (namespace, job id) -> eval id currently queued/outstanding
        self.job_evals: Dict[Tuple[str, str], str] = {}
        # (namespace, job id) -> heap of blocked-behind evals
        self.blocked: Dict[Tuple[str, str], _PendingHeap] = {}
        # scheduler type -> ready heap
        self.ready: Dict[str, _PendingHeap] = {}
        # eval id -> unack record
        self.unack: Dict[str, _Unack] = tracked_dict(
            "eval_broker.EvalBroker.unack", {})
        # token -> eval to requeue on Ack
        self.requeue: Dict[str, Evaluation] = {}
        # eval id -> wait timer (Evaluation.wait_ns)
        self.time_wait: Dict[str, threading.Timer] = {}
        # delayed evals (wait_until) handled by a timer per eval too
        self._delayed: Dict[str, threading.Timer] = {}
        # workers currently parked in dequeue() waiting for a ready eval
        # (flight-recorder probe: high waiters + nonzero ready = dequeue
        # contention; high waiters + zero ready = starvation upstream)
        self._dequeue_waiters = 0

    # ------------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            prev = self.enabled
            self.enabled = enabled
        if prev and not enabled:
            self.flush()

    # ------------------------------------------------------------------

    def enqueue(self, evaluation: Evaluation) -> None:
        with self._lock:
            self._process_enqueue(evaluation, "")

    def enqueue_all(self, evals: Dict[str, Tuple[Evaluation, str]]) -> None:
        """{eval_id: (eval, token)} — token set means requeue-after-ack."""
        with self._lock:
            for _, (evaluation, token) in evals.items():
                self._process_enqueue(evaluation, token)

    def _process_enqueue(self, evaluation: Evaluation, token: str) -> None:
        if not self.enabled:
            return
        if evaluation.id in self.evals:
            if token == "":
                return
            # Updating an outstanding eval: requeue once the current
            # delivery acks.
            self.requeue[token] = evaluation
            return

        if evaluation.wait_until_ns and evaluation.wait_until_ns > time.time_ns():
            delay = (evaluation.wait_until_ns - time.time_ns()) / 1e9
            timer = threading.Timer(delay, self._wait_done, args=(evaluation,))
            timer.daemon = True
            self._delayed[evaluation.id] = timer
            self.evals[evaluation.id] = 0
            timer.start()
            return

        if evaluation.wait_ns:
            delay = evaluation.wait_ns / 1e9
            timer = threading.Timer(delay, self._wait_done, args=(evaluation,))
            timer.daemon = True
            self.time_wait[evaluation.id] = timer
            self.evals[evaluation.id] = 0
            timer.start()
            return

        self.evals[evaluation.id] = 0
        self._enqueue_locked(evaluation, evaluation.type)

    def _wait_done(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.time_wait.pop(evaluation.id, None)
            self._delayed.pop(evaluation.id, None)
            if not self.enabled:
                return
            self._enqueue_locked(evaluation, evaluation.type)

    def _enqueue_locked(self, evaluation: Evaluation, queue: str) -> None:
        if not self.enabled:
            return
        namespaced = (evaluation.namespace, evaluation.job_id)
        if evaluation.job_id:
            existing = self.job_evals.get(namespaced)
            if existing is None:
                self.job_evals[namespaced] = evaluation.id
            elif existing != evaluation.id:
                self.blocked.setdefault(namespaced, _PendingHeap()).push(evaluation)
                return
        self.ready.setdefault(queue, _PendingHeap()).push(evaluation)
        if queue != FAILED_QUEUE:
            # trace record opens when the eval becomes READY (nack
            # re-enqueues open a fresh one; the failed queue never
            # delivers, so it gets none)
            _trace.on_enqueue(evaluation)
        # ONE eval became ready: wake a bounded number of waiters, not
        # the whole worker pool — notify_all turns a C1M registration
        # storm into O(workers x evals) spurious wakeups all contending
        # for the broker lock (and the GIL). Waking 2 covers the case
        # where the first woken waiter's scheduler filter skips this
        # queue; any residual miss self-heals within the dequeue loop's
        # 1s re-scan timeout.
        self._cond.notify(2)

    # ------------------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[Evaluation], str]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                ev_token = self._scan(schedulers)
                if ev_token is not None:
                    return ev_token
                if deadline is None:
                    self._dequeue_waiters += 1
                    try:
                        self._cond.wait(timeout=1.0)
                    finally:
                        self._dequeue_waiters -= 1
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, ""
                    self._dequeue_waiters += 1
                    try:
                        self._cond.wait(timeout=remaining)
                    finally:
                        self._dequeue_waiters -= 1
                if not self.enabled:
                    return None, ""

    def _scan(self, schedulers: List[str]) -> Optional[Tuple[Evaluation, str]]:
        if not self.enabled:
            return None
        best_queue = None
        best_priority = -1
        for sched in schedulers:
            heap = self.ready.get(sched)
            if heap and len(heap):
                ev = heap.peek()
                if ev.priority > best_priority:
                    best_priority = ev.priority
                    best_queue = sched
        if best_queue is None:
            return None
        evaluation = self.ready[best_queue].pop()
        token = generate_uuid()
        self.evals[evaluation.id] = self.evals.get(evaluation.id, 0) + 1
        # the delivery counter doubles as the OCC retry count on the trace
        _trace.on_dequeue(evaluation.id, self.evals[evaluation.id])
        timer = threading.Timer(self.nack_timeout, self._nack_expired, args=(evaluation.id, token))
        timer.daemon = True
        self.unack[evaluation.id] = _Unack(evaluation, token, timer)
        timer.start()
        return evaluation, token

    def _nack_expired(self, eval_id: str, token: str) -> None:
        try:
            self.nack(eval_id, token)
        except (NotOutstandingError, TokenMismatchError):
            pass

    # ------------------------------------------------------------------

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            unack = self.unack.get(eval_id)
            return unack.token if unack else None

    def ack(self, eval_id: str, token: str) -> None:
        # chaos hook: a fault here is a LOST ack — the delivery stays
        # unacked and the nack timer redelivers it (every caller survives
        # an ack exception; the applier releases its slot in a finally)
        chaos_fire("broker_ack", eval_id=eval_id)
        with self._lock:
            unack = self.unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(eval_id)
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()
            del self.unack[eval_id]
            del self.evals[eval_id]
            # close BEFORE the requeue below may reopen the same id
            _trace.on_ack(eval_id)
            # close the unblock->place storm sample (no-op for evals
            # that never sat in BlockedEvals)
            _capacity.observe_placed(eval_id)

            namespaced = (unack.eval.namespace, unack.eval.job_id)
            if self.job_evals.get(namespaced) == eval_id:
                del self.job_evals[namespaced]
                # unblock the next eval for this job
                blocked = self.blocked.get(namespaced)
                if blocked is not None and len(blocked):
                    nxt = blocked.pop()
                    if not len(blocked):
                        del self.blocked[namespaced]
                    self._enqueue_locked(nxt, nxt.type)

            requeued = self.requeue.pop(token, None)
            if requeued is not None:
                self._process_enqueue(requeued, "")

    def nack(self, eval_id: str, token: str) -> None:
        with self._lock:
            self.requeue.pop(token, None)
            unack = self.unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(eval_id)
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()
            del self.unack[eval_id]

            prev_dequeues = self.evals.get(eval_id, 0)
            if prev_dequeues >= self.delivery_limit:
                _trace.on_nack(eval_id, failed=True)
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
                return
            _trace.on_nack(eval_id)

            delay = self._nack_reenqueue_delay(prev_dequeues)
            timer = threading.Timer(delay, self._wait_done, args=(unack.eval,))
            timer.daemon = True
            self.time_wait[eval_id] = timer
            timer.start()

    def _nack_reenqueue_delay(self, prev_dequeues: int) -> float:
        if prev_dequeues <= 1:
            return self.initial_nack_delay
        return float(prev_dequeues - 1) * self.subsequent_nack_delay

    # ------------------------------------------------------------------

    def pause_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._lock:
            unack = self.unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(eval_id)
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()

    def resume_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._lock:
            unack = self.unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(eval_id)
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            timer = threading.Timer(self.nack_timeout, self._nack_expired, args=(eval_id, token))
            timer.daemon = True
            unack.nack_timer = timer
            timer.start()

    # ------------------------------------------------------------------

    def flush(self) -> None:
        with self._lock:
            for unack in self.unack.values():
                unack.nack_timer.cancel()
            for timer in self.time_wait.values():
                timer.cancel()
            for timer in self._delayed.values():
                timer.cancel()
            self.evals.clear()
            self.job_evals.clear()
            self.blocked.clear()
            self.ready.clear()
            self.unack.clear()
            self.requeue.clear()
            self.time_wait.clear()
            self._delayed.clear()
            self._cond.notify_all()
        _trace.on_flush()

    def stats(self) -> Dict[str, object]:
        with self._lock:
            by_sched = {}
            total_ready = 0
            for sched, heap in self.ready.items():
                by_sched[sched] = len(heap)
                total_ready += len(heap)
            return {
                "total_ready": total_ready,
                "total_unacked": len(self.unack),
                "total_blocked": sum(len(h) for h in self.blocked.values()),
                "total_waiting": len(self.time_wait) + len(self._delayed),
                "dequeue_waiters": self._dequeue_waiters,
                "by_scheduler": by_sched,
            }
