"""Finite state machine: applies replicated log entries to the StateStore.

Fills the role of reference ``nomad/fsm.go`` — one dispatch point so every
server materializes identical state from the same log. Log entries are
(type, payload) tuples of plain Python objects (the in-proc log passes them
by reference; a wire codec slots in at the raft boundary).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

from ..state import StateStore
from ..structs.structs import (
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_PENDING,
    Allocation,
    Deployment,
    Evaluation,
    Job,
    Node,
    SchedulerConfiguration,
)

# Log entry types (reference fsm.go:190-252 dispatch)
NODE_REGISTER = "node-register"
NODE_DEREGISTER = "node-deregister"
NODE_STATUS_UPDATE = "node-status-update"
NODE_DRAIN_UPDATE = "node-drain-update"
NODE_ELIGIBILITY_UPDATE = "node-eligibility-update"
JOB_REGISTER = "job-register"
JOB_DEREGISTER = "job-deregister"
EVAL_UPDATE = "eval-update"
EVAL_DELETE = "eval-delete"
ALLOC_UPDATE = "alloc-update"
ALLOC_CLIENT_UPDATE = "alloc-client-update"
ALLOC_UPDATE_DESIRED_TRANSITION = "alloc-update-desired-transition"
APPLY_PLAN_RESULTS = "apply-plan-results"
APPLY_PLAN_RESULTS_BATCH = "apply-plan-results-batch"
DEPLOYMENT_STATUS_UPDATE = "deployment-status-update"
DEPLOYMENT_PROMOTE = "deployment-promote"
DEPLOYMENT_ALLOC_HEALTH = "deployment-alloc-health"
DEPLOYMENT_DELETE = "deployment-delete"
SCHEDULER_CONFIG = "scheduler-config"
BATCH_NODE_UPDATE_DRAIN = "batch-node-update-drain"
JOB_STABILITY = "job-stability"
PERIODIC_LAUNCH = "periodic-launch"
ACL_POLICY_UPSERT = "acl-policy-upsert"
ACL_POLICY_DELETE = "acl-policy-delete"
ACL_TOKEN_UPSERT = "acl-token-upsert"
VAULT_ACCESSOR_UPSERT = "vault-accessor-upsert"
VAULT_ACCESSOR_DELETE = "vault-accessor-delete"
AUTOPILOT_CONFIG = "autopilot-config"
ACL_TOKEN_DELETE = "acl-token-delete"
ACL_TOKEN_BOOTSTRAP = "acl-token-bootstrap"


class NomadFSM:
    def __init__(self, state: Optional[StateStore] = None, logger=None) -> None:
        self.state = state or StateStore()
        self.logger = logger or logging.getLogger("nomad_tpu.fsm")
        # leader-only hooks, set by the server when it holds leadership
        self.on_eval_upserted: Optional[Callable[[Evaluation], None]] = None
        self.on_capacity_change: Optional[Callable[[str, int], None]] = None
        # index<->time witnesses accumulate on every server (leader and
        # followers) so GC cutoffs survive leader transitions
        # (reference fsm.go witnesses inside Apply).
        self.timetable = None
        # blocking-query wakeups (watch/hub.WatchHub), attached by the
        # server on EVERY replica — followers notify their local hub so
        # stale reads park/wake against follower state. Standalone FSMs
        # (unit tests, parity oracles) leave it None and skip notify.
        # (annotated so the static lock-order graph types the attribute
        # and sees the apply -> hub._lock edge the runtime witness sees)
        self.watch_hub: Optional["WatchHub"] = None

    def apply(self, index: int, entry_type: str, payload) -> object:
        handler = _DISPATCH.get(entry_type)
        if handler is None:
            raise ValueError(f"unknown log entry type {entry_type!r}")
        if self.timetable is not None:
            self.timetable.witness(index)
        result = handler(self, index, payload)
        # notify AFTER the write is materialized, outside the dispatch
        # table (the hub's coalescing timer/clock must stay unreachable
        # from the fsm-determinism roots — notify only signals, it never
        # feeds state back into handlers)
        if self.watch_hub is not None:
            self.watch_hub.notify(index, _watch_touched(entry_type, payload))
        return result

    # -- handlers ----------------------------------------------------------

    def _apply_node_register(self, index: int, node: Node):
        self.state.upsert_node(index, node)
        stored = self.state.node_by_id(node.id)
        if self.on_capacity_change is not None and stored is not None and stored.ready():
            self.on_capacity_change(stored.computed_class, index)

    def _apply_node_deregister(self, index: int, node_id: str):
        self.state.delete_node(index, node_id)

    def _apply_node_status_update(self, index: int, payload):
        node_id, status = payload
        self.state.update_node_status(index, node_id, status)
        node = self.state.node_by_id(node_id)
        if self.on_capacity_change is not None and node is not None and node.ready():
            self.on_capacity_change(node.computed_class, index)

    def _apply_node_drain_update(self, index: int, payload):
        node_id, drain, mark_eligible = payload
        self.state.update_node_drain(index, node_id, drain, mark_eligible)

    def _apply_node_eligibility_update(self, index: int, payload):
        node_id, eligibility = payload
        self.state.update_node_eligibility(index, node_id, eligibility)
        node = self.state.node_by_id(node_id)
        if self.on_capacity_change is not None and node is not None and node.ready():
            self.on_capacity_change(node.computed_class, index)

    def _apply_job_register(self, index: int, job: Job):
        self.state.upsert_job(index, job)

    def _apply_job_deregister(self, index: int, payload):
        namespace, job_id, purge = payload
        if purge:
            self.state.delete_job(index, namespace, job_id)
        else:
            job = self.state.job_by_id(namespace, job_id)
            if job is not None:
                stopped = job.copy()
                stopped.stop = True
                self.state.upsert_job(index, stopped)

    def _apply_eval_update(self, index: int, evals: List[Evaluation]):
        self.state.upsert_evals(index, evals)
        if self.on_eval_upserted is not None:
            for ev in evals:
                stored = self.state.eval_by_id(ev.id)
                if stored is not None:
                    self.on_eval_upserted(stored)

    def _apply_eval_delete(self, index: int, payload):
        eval_ids, alloc_ids = payload
        self.state.delete_eval(index, eval_ids, alloc_ids)

    def _apply_alloc_update(self, index: int, allocs: List[Allocation]):
        self.state.upsert_allocs(index, allocs)

    def _apply_alloc_client_update(self, index: int, allocs: List[Allocation]):
        self.state.update_allocs_from_client(index, allocs)
        # terminal client states free capacity -> unblock
        if self.on_capacity_change is not None:
            for alloc in allocs:
                if alloc.client_terminal_status():
                    stored = self.state.alloc_by_id(alloc.id)
                    node = self.state.node_by_id(stored.node_id) if stored else None
                    if node is not None:
                        self.on_capacity_change(node.computed_class, index)

    def _apply_alloc_update_desired_transition(self, index: int, payload):
        transitions, evals = payload
        for alloc_id, transition in transitions.items():
            alloc = self.state.alloc_by_id(alloc_id)
            if alloc is None:
                continue
            updated = alloc.copy_skip_job()
            updated.desired_transition = transition
            updated.modify_index = index
            self.state.upsert_allocs(index, [updated])
        if evals:
            self._apply_eval_update(index, evals)

    def _apply_plan_results(self, index: int, payload):
        self.state.upsert_plan_results(
            index,
            alloc_updates=payload["alloc_updates"],
            allocs_stopped=payload["allocs_stopped"],
            allocs_preempted=payload.get("allocs_preempted", []),
            dense_placements=payload.get("dense_placements", []),
            deployment=payload.get("deployment"),
            deployment_updates=payload.get("deployment_updates"),
            eval_id=payload.get("eval_id", ""),
            timestamp_ns=payload.get("timestamp_ns", 0),
        )
        if payload.get("preemption_evals"):
            self._apply_eval_update(index, payload["preemption_evals"])
        # stopped allocs free capacity
        if self.on_capacity_change is not None:
            seen = set()
            for alloc in payload["allocs_stopped"]:
                node = self.state.node_by_id(alloc.node_id)
                if node is not None and node.computed_class not in seen:
                    seen.add(node.computed_class)
                    self.on_capacity_change(node.computed_class, index)

    def _apply_plan_results_batch(self, index: int, payloads):
        """One raft entry carrying SEVERAL plans' results — the leader's
        applier groups queued plans so the commit path pays raft/FSM
        dispatch once per batch instead of once per plan (the reference
        serializes per plan at plan_apply.go:45–70; batching is the
        TPU-era answer to C1M commit rates, where per-plan round trips
        dominate). Sequential application preserves per-plan semantics:
        the applier evaluated plan k+1 against a snapshot that already
        contained plan k's results.

        Payloads are independent plans, so failures are isolated per
        payload: the rest of the batch still applies (a shared failure
        would tell workers whose placements DID commit that they
        failed), and the per-payload error list returns to the leader's
        apply waiter so it can respond to each plan accurately. The
        errors are data-deterministic, so every replica partitions the
        batch identically."""
        from ..utils import metrics

        errors = []
        committed_dense = 0
        for payload in payloads:
            try:
                self._apply_plan_results(index, payload)
                errors.append(None)
                for block in payload.get("dense_placements", []):
                    committed_dense += len(block.ids)
            except Exception as e:  # noqa: BLE001 — isolate to this plan
                logging.getLogger("nomad_tpu.fsm").exception(
                    "plan payload in batch failed to apply"
                )
                errors.append(str(e) or e.__class__.__name__)
        if committed_dense:
            # commit-side ground truth for the async pipeline: placements
            # that actually landed in the FSM, as opposed to the
            # dispatch-side "submitted" counters upstream
            metrics.incr_counter(
                "nomad.fsm.dense_placements_committed", committed_dense
            )
        return errors

    def _apply_deployment_status_update(self, index: int, payload):
        update, job, evaluation = payload
        d = self.state.deployment_by_id(update.deployment_id)
        if d is not None:
            nd = d.copy()
            nd.status = update.status
            nd.status_description = update.status_description
            self.state.upsert_deployment(index, nd)
        if job is not None:
            self.state.upsert_job(index, job)
        if evaluation is not None:
            self._apply_eval_update(index, [evaluation])

    def _apply_deployment_promote(self, index: int, payload):
        deployment_id, groups, description, evaluation = payload
        d = self.state.deployment_by_id(deployment_id)
        if d is not None:
            nd = d.copy()
            for group, dstate in nd.task_groups.items():
                if groups is None or group in groups:
                    dstate.promoted = True
            nd.status_description = description
            self.state.upsert_deployment(index, nd)
            # canaries lose canary status on promote
            for alloc_id in [
                a for s in (d.task_groups or {}).values() for a in s.placed_canaries
            ]:
                alloc = self.state.alloc_by_id(alloc_id)
                if alloc is not None and alloc.deployment_status is not None:
                    updated = alloc.copy_skip_job()
                    updated.deployment_status.canary = False
                    self.state.upsert_allocs(index, [updated])
        if evaluation is not None:
            self._apply_eval_update(index, [evaluation])

    def _apply_deployment_alloc_health(self, index: int, payload):
        deployment_id, healthy_ids, unhealthy_ids, timestamp_ns, dstatus, evaluation = payload
        self.state.update_deployment_alloc_health(
            index, deployment_id, healthy_ids, unhealthy_ids, timestamp_ns
        )
        if dstatus is not None:
            self._apply_deployment_status_update(index, (dstatus, None, None))
        if evaluation is not None:
            self._apply_eval_update(index, [evaluation])

    def _apply_deployment_delete(self, index: int, deployment_ids: List[str]):
        self.state.delete_deployment(index, deployment_ids)

    def _apply_scheduler_config(self, index: int, config: SchedulerConfiguration):
        self.state.scheduler_set_config(index, config)

    def _apply_job_stability(self, index: int, payload):
        namespace, job_id, version, stable = payload
        self.state.update_job_stability(index, namespace, job_id, version, stable)

    def _apply_periodic_launch(self, index: int, payload):
        namespace, job_id, launch_ns = payload
        self.state.upsert_periodic_launch(index, namespace, job_id, launch_ns)

    def _apply_batch_node_drain(self, index: int, payload):
        for node_id, (drain, mark_eligible) in payload.items():
            try:
                self.state.update_node_drain(index, node_id, drain, mark_eligible)
            except KeyError:
                pass

    # -- snapshot/restore --------------------------------------------------

    def _apply_acl_policy_upsert(self, index: int, policies):
        self.state.upsert_acl_policies(index, policies)

    def _apply_acl_policy_delete(self, index: int, names):
        self.state.delete_acl_policies(index, names)

    def _apply_acl_token_upsert(self, index: int, tokens):
        self.state.upsert_acl_tokens(index, tokens)

    def _apply_acl_token_delete(self, index: int, accessors):
        self.state.delete_acl_tokens(index, accessors)

    def _apply_acl_token_bootstrap(self, index: int, token):
        self.state.bootstrap_acl_token(index, token)

    def _apply_vault_accessor_upsert(self, index: int, records):
        self.state.upsert_vault_accessors(index, records)

    def _apply_vault_accessor_delete(self, index: int, alloc_ids):
        self.state.delete_vault_accessors(index, alloc_ids)

    def _apply_autopilot_config(self, index: int, config):
        self.state.autopilot_set_config(index, config)

    def snapshot(self) -> StateStore:
        return self.state.snapshot()

    def restore(self, snapshot: StateStore) -> None:
        self.state = snapshot
        # the whole store changed identity: every parked watcher must
        # re-query against the NEW tables, whatever it was watching
        if self.watch_hub is not None:
            self.watch_hub.notify_all(snapshot.latest_index)


# Every handler reachable from this table replays on every replica from
# the raft log — it must be a pure function of (state, index, payload).
# The fsm-determinism lint rule (nomad_tpu/analysis/) enforces that no
# handler, directly or transitively, reads the wall clock or RNG;
# timestamps/UUIDs must be stamped by the proposer and carried in the
# log entry payload.
_DISPATCH: Dict[str, Callable] = {
    NODE_REGISTER: NomadFSM._apply_node_register,
    NODE_DEREGISTER: NomadFSM._apply_node_deregister,
    NODE_STATUS_UPDATE: NomadFSM._apply_node_status_update,
    NODE_DRAIN_UPDATE: NomadFSM._apply_node_drain_update,
    NODE_ELIGIBILITY_UPDATE: NomadFSM._apply_node_eligibility_update,
    JOB_REGISTER: NomadFSM._apply_job_register,
    JOB_DEREGISTER: NomadFSM._apply_job_deregister,
    EVAL_UPDATE: NomadFSM._apply_eval_update,
    EVAL_DELETE: NomadFSM._apply_eval_delete,
    ALLOC_UPDATE: NomadFSM._apply_alloc_update,
    ALLOC_CLIENT_UPDATE: NomadFSM._apply_alloc_client_update,
    ALLOC_UPDATE_DESIRED_TRANSITION: NomadFSM._apply_alloc_update_desired_transition,
    APPLY_PLAN_RESULTS: NomadFSM._apply_plan_results,
    APPLY_PLAN_RESULTS_BATCH: NomadFSM._apply_plan_results_batch,
    DEPLOYMENT_STATUS_UPDATE: NomadFSM._apply_deployment_status_update,
    DEPLOYMENT_PROMOTE: NomadFSM._apply_deployment_promote,
    DEPLOYMENT_ALLOC_HEALTH: NomadFSM._apply_deployment_alloc_health,
    DEPLOYMENT_DELETE: NomadFSM._apply_deployment_delete,
    SCHEDULER_CONFIG: NomadFSM._apply_scheduler_config,
    BATCH_NODE_UPDATE_DRAIN: NomadFSM._apply_batch_node_drain,
    JOB_STABILITY: NomadFSM._apply_job_stability,
    PERIODIC_LAUNCH: NomadFSM._apply_periodic_launch,
    ACL_POLICY_UPSERT: NomadFSM._apply_acl_policy_upsert,
    ACL_POLICY_DELETE: NomadFSM._apply_acl_policy_delete,
    ACL_TOKEN_UPSERT: NomadFSM._apply_acl_token_upsert,
    ACL_TOKEN_DELETE: NomadFSM._apply_acl_token_delete,
    ACL_TOKEN_BOOTSTRAP: NomadFSM._apply_acl_token_bootstrap,
    VAULT_ACCESSOR_UPSERT: NomadFSM._apply_vault_accessor_upsert,
    VAULT_ACCESSOR_DELETE: NomadFSM._apply_vault_accessor_delete,
    AUTOPILOT_CONFIG: NomadFSM._apply_autopilot_config,
}


# -- watch-hub touch maps ----------------------------------------------------
#
# Which (table, key) pairs each entry type dirties, for post-apply watch
# notification. ``key=None`` means a bulk write to the table (wakes every
# watcher of it, row-level ones included). Key conventions match the read
# endpoints' subscriptions: nodes/evals/allocs/deployments key on their id,
# jobs on (namespace, id). The map errs TOWARD waking: a spurious wake
# costs one re-query; a missed one strands a watcher until its deadline —
# hence the unknown-entry fallback notifies every table.

_WATCH_ALL = tuple((t, None) for t in (
    "nodes", "jobs", "evals", "allocs", "deployments",
))


def _touched_plan_results(payload):
    # allocs stay a bulk touch: dense placements can carry thousands of
    # ids per plan and enumerating them on the apply hot path costs more
    # than the spurious row-watcher re-queries it would save. Evals and
    # deployments are few per plan, so those enumerate precisely — a plan
    # storm must not wake every parked row-level eval watcher (the serve
    # bench measures exactly this).
    out = [("allocs", None)]
    eval_id = payload.get("eval_id", "")
    out.append(("evals", eval_id or None))
    for ev in payload.get("preemption_evals") or ():
        out.append(("evals", ev.id))
    dep = payload.get("deployment")
    if dep is not None:
        out.append(("deployments", dep.id))
    for upd in payload.get("deployment_updates") or ():
        out.append(("deployments", upd.deployment_id))
    return out


_WATCH_TOUCHED = {
    NODE_REGISTER: lambda p: [("nodes", p.id)],
    NODE_DEREGISTER: lambda p: [("nodes", p)],
    NODE_STATUS_UPDATE: lambda p: [("nodes", p[0])],
    NODE_DRAIN_UPDATE: lambda p: [("nodes", p[0])],
    NODE_ELIGIBILITY_UPDATE: lambda p: [("nodes", p[0])],
    BATCH_NODE_UPDATE_DRAIN: lambda p: [("nodes", nid) for nid in p],
    JOB_REGISTER: lambda p: [("jobs", (p.namespace, p.id))],
    JOB_DEREGISTER: lambda p: [("jobs", (p[0], p[1]))],
    EVAL_UPDATE: lambda p: [("evals", ev.id) for ev in p],
    EVAL_DELETE: lambda p: (
        [("evals", eid) for eid in p[0]] + [("allocs", aid) for aid in p[1]]
    ),
    ALLOC_UPDATE: lambda p: [("allocs", a.id) for a in p],
    ALLOC_CLIENT_UPDATE: lambda p: [("allocs", a.id) for a in p],
    ALLOC_UPDATE_DESIRED_TRANSITION: lambda p: (
        [("allocs", aid) for aid in p[0]] + [("evals", ev.id) for ev in p[1] or ()]
    ),
    APPLY_PLAN_RESULTS: _touched_plan_results,
    APPLY_PLAN_RESULTS_BATCH: lambda p: [
        t for payload in p for t in _touched_plan_results(payload)
    ],
    DEPLOYMENT_STATUS_UPDATE: lambda p: (
        [("deployments", p[0].deployment_id)]
        + ([("jobs", (p[1].namespace, p[1].id))] if p[1] is not None else [])
        + ([("evals", p[2].id)] if p[2] is not None else [])
    ),
    DEPLOYMENT_PROMOTE: lambda p: (
        [("deployments", p[0]), ("allocs", None)]
        + ([("evals", p[3].id)] if p[3] is not None else [])
    ),
    DEPLOYMENT_ALLOC_HEALTH: lambda p: (
        [("deployments", p[0]), ("allocs", None)]
        + ([("evals", p[5].id)] if p[5] is not None else [])
    ),
    DEPLOYMENT_DELETE: lambda p: [("deployments", did) for did in p],
    JOB_STABILITY: lambda p: [("jobs", (p[0], p[1]))],
    PERIODIC_LAUNCH: lambda p: [("jobs", (p[0], p[1]))],
    # config/ACL/vault entries touch no watched read table
    SCHEDULER_CONFIG: lambda p: (),
    AUTOPILOT_CONFIG: lambda p: (),
    ACL_POLICY_UPSERT: lambda p: (),
    ACL_POLICY_DELETE: lambda p: (),
    ACL_TOKEN_UPSERT: lambda p: (),
    ACL_TOKEN_DELETE: lambda p: (),
    ACL_TOKEN_BOOTSTRAP: lambda p: (),
    VAULT_ACCESSOR_UPSERT: lambda p: (),
    VAULT_ACCESSOR_DELETE: lambda p: (),
}


def _watch_touched(entry_type: str, payload):
    fn = _WATCH_TOUCHED.get(entry_type)
    if fn is None:
        return _WATCH_ALL
    try:
        return fn(payload)
    except Exception:  # noqa: BLE001 — never let a notify map break apply
        return _WATCH_ALL
