"""Server-side node liveness via heartbeat TTL timers.

Semantics follow reference ``nomad/heartbeat.go`` — each registered node has
a TTL timer reset on every heartbeat; expiry marks the node down and spawns
node-update evals so its allocs are marked lost and rescheduled.
"""
from __future__ import annotations

import logging
import random
import threading
from typing import Dict

from ..chaos.injector import fire as chaos_fire
from ..structs.structs import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_NODE_UPDATE,
    NODE_STATUS_DOWN,
    Evaluation,
)
from .fsm import EVAL_UPDATE, NODE_STATUS_UPDATE
from ..utils.lock_witness import witness_lock


class HeartbeatTimers:
    def __init__(self, server, min_ttl: float = 10.0, max_ttl: float = 30.0) -> None:
        self.server = server
        self.min_ttl = min_ttl
        self.max_ttl = max_ttl
        self.logger = logging.getLogger("nomad_tpu.heartbeat")
        self._lock = witness_lock("heartbeat.HeartbeatTimers._lock")
        self._timers: Dict[str, threading.Timer] = {}
        self.enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                for t in self._timers.values():
                    t.cancel()
                self._timers.clear()

    def reset_heartbeat_timer(self, node_id: str) -> float:
        """(Re)arm a node's TTL; returns the TTL handed back to the client."""
        # chaos hook: a fault here is a DROPPED heartbeat — the node's
        # TTL timer keeps its old deadline; enough drops in a row and it
        # expires, marking the node down (the real failure this models)
        chaos_fire("heartbeat", node_id=node_id)
        ttl = self.min_ttl + random.random() * (self.max_ttl - self.min_ttl)
        with self._lock:
            if not self.enabled:
                return ttl
            old = self._timers.pop(node_id, None)
            if old is not None:
                old.cancel()
            cell = []
            timer = threading.Timer(ttl, self._invalidate, args=(node_id, cell))
            cell.append(timer)
            timer.daemon = True
            self._timers[node_id] = timer
            timer.start()
        return ttl

    def num_active(self) -> int:
        with self._lock:
            return len(self._timers)

    def clear_heartbeat_timer(self, node_id: str) -> None:
        with self._lock:
            old = self._timers.pop(node_id, None)
            if old is not None:
                old.cancel()

    def _invalidate(self, node_id: str, cell) -> None:
        """Missed TTL: node down + evals for each job with allocs on it."""
        with self._lock:
            current = self._timers.get(node_id)
            if not cell or current is not cell[0]:
                # A racing heartbeat re-armed the TTL; this expiry is stale.
                return
            del self._timers[node_id]
            if not self.enabled:
                return
        self.logger.warning("node %s missed heartbeat, marking down", node_id)
        try:
            self.server.raft_apply(NODE_STATUS_UPDATE, (node_id, NODE_STATUS_DOWN))
        except Exception:  # noqa: BLE001 — lost leadership etc.
            self.logger.exception("failed to invalidate heartbeat for %s", node_id)
            return
        self.server.create_node_evals(node_id)
