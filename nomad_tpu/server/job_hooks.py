"""Job admission mutators (reference nomad/job_endpoint_hook_connect.go).

``job_connect_hook`` realizes groupConnectHook (:99): every group service
with a Consul Connect sidecar stanza gets a sidecar proxy task injected
into its task group (unless one already exists) plus a dynamic proxy port
on the group network. Runs at Job.Register admission, before the job hits
raft, so schedulers and clients only ever see the expanded job.
"""
from __future__ import annotations

from typing import List, Optional

from ..structs.structs import (
    CONNECT_PROXY_PREFIX,
    NetworkResource,
    Port,
    Resources,
    Service,
    Task,
    TaskGroup,
)


def sidecar_task_name(service_name: str) -> str:
    return f"{CONNECT_PROXY_PREFIX}-{service_name}"


def sidecar_kind(service_name: str) -> str:
    return f"{CONNECT_PROXY_PREFIX}:{service_name}"


def _get_sidecar_task(tg: TaskGroup, service_name: str) -> Optional[Task]:
    kind = sidecar_kind(service_name)
    for t in tg.tasks:
        if getattr(t, "kind", "") == kind:
            return t
    return None


def _new_connect_task(service: Service) -> Task:
    """newConnectTask (:150): the default Envoy sidecar. The
    ``sidecar_task`` stanza overrides driver/config/resources — which is
    also how non-docker environments run a stand-in proxy."""
    task = Task(
        name=sidecar_task_name(service.name),
        driver="docker",
        config={
            "image": "envoyproxy/envoy:v1.11.2@sha256:a7769160c9c1a55bb8d07a3b71ce5d64f72b1f665f10d81aa1581bc3cf850d09",
            "args": [
                "-c", "${NOMAD_SECRETS_DIR}/envoy_bootstrap.json",
                "-l", "${meta.connect.log_level}",
            ],
        },
        resources=Resources(cpu=250, memory_mb=128),
    )
    task.kind = sidecar_kind(service.name)
    return task


def group_connect_validate(tg: TaskGroup) -> None:
    """groupConnectValidate (:171): sidecars need exactly one group
    network to attach the proxy port to."""
    for s in tg.services:
        if s.has_sidecar():
            if len(tg.networks) != 1:
                raise ValueError(
                    "Consul Connect sidecars require exactly 1 network, "
                    f"found {len(tg.networks)} in group {tg.name!r}"
                )
            break


def group_connect_hook(tg: TaskGroup) -> None:
    """groupConnectHook (:99): inject the sidecar task + proxy port."""
    for service in tg.services:
        if not service.has_sidecar():
            continue
        task = _get_sidecar_task(tg, service.name)
        if task is None:
            task = _new_connect_task(service)
            # merge the user's sidecar_task overrides (SidecarTask
            # MergeIntoTask)
            override = (service.connect or {}).get("sidecar_task") or {}
            if override.get("name"):
                task.name = override["name"]
            if override.get("driver"):
                task.driver = override["driver"]
            if override.get("config") is not None:
                task.config = dict(override["config"])
            if override.get("resources") is not None:
                res = override["resources"]
                task.resources = Resources(
                    cpu=res.get("cpu", 250),
                    memory_mb=res.get("memory_mb", 128),
                )
            if any(t.name == task.name for t in tg.tasks):
                from ..structs.structs import generate_uuid

                task.name = f"{task.name}-{generate_uuid()[:6]}"
            tg.tasks.append(task)

        # the sidecar proxy listens on a dynamic group port
        port_label = f"{CONNECT_PROXY_PREFIX}-{service.name}"
        net = tg.networks[0]
        if not any(p.label == port_label for p in net.dynamic_ports):
            net.dynamic_ports.append(Port(label=port_label))


def job_connect_hook(job) -> None:
    """jobConnectHook.Mutate (:55) + Validate: expand every task group."""
    for tg in job.task_groups:
        if not any(s.has_sidecar() for s in tg.services):
            continue
        group_connect_validate(tg)
        group_connect_hook(tg)
