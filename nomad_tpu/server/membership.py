"""Server gossip membership: peer discovery, leader tags, federation.

Fills the role of reference ``nomad/serf.go`` (serf event loop →
``reconcileCh`` → peer add/remove, leader.go:859/:952) plus the ``peers``
region map (server.go:156) that powers cross-region RPC forwarding
(rpc.go:502 forwardRegion). Each server joins the gossip pool with tags
identifying its region/datacenter/RPC address, mirroring the reference's
serf tags (serf.go members are "<name>.<region>"); the current leader
re-tags itself ``leader=1`` so followers learn the forwarding target
without a separate election channel (the reference derives this from raft;
until the wire raft lands — see raft.py — gossip tags carry it).
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..gossip.memberlist import Member, Memberlist, MemberlistConfig
from ..utils.lock_witness import witness_rlock


@dataclass
class ServerMeta:
    """A gossiped nomad server (reference nomad/util.go serverParts)."""

    name: str
    region: str
    datacenter: str
    rpc_host: str
    rpc_port: int
    expect: int
    is_leader: bool

    @property
    def rpc_addr(self) -> Tuple[str, int]:
        return (self.rpc_host, self.rpc_port)


def _parse_server(member: Member) -> Optional[ServerMeta]:
    tags = member.tags
    if tags.get("role") != "nomad":
        return None
    rpc = tags.get("rpc_addr", "")
    if ":" not in rpc:
        return None
    host, port = rpc.rsplit(":", 1)
    try:
        return ServerMeta(
            name=member.name,
            region=tags.get("region", "global"),
            datacenter=tags.get("dc", "dc1"),
            rpc_host=host,
            rpc_port=int(port),
            expect=int(tags.get("expect", "1")),
            is_leader=tags.get("leader") == "1",
        )
    except ValueError:
        return None


class ServerMembership:
    """Gossip participant for one server; maintains the region→servers map."""

    def __init__(
        self,
        name: str,
        region: str,
        datacenter: str,
        rpc_addr: Tuple[str, int],
        bind_host: str = "127.0.0.1",
        bind_port: int = 0,
        advertise_host: str = "",
        expect: int = 1,
        config: Optional[MemberlistConfig] = None,
        encrypt_key: bytes = b"",
    ) -> None:
        self.region = region
        self.logger = logging.getLogger(f"nomad_tpu.membership.{name}")
        self._lock = witness_rlock("membership.ServerMembership._lock")
        # region → {member name → ServerMeta}; includes ourselves
        self.peers: Dict[str, Dict[str, ServerMeta]] = {}
        self._tags = {
            "role": "nomad",
            "region": region,
            "dc": datacenter,
            "rpc_addr": f"{rpc_addr[0]}:{rpc_addr[1]}",
            "expect": str(expect),
            "build": "0.10.2-tpu",
        }
        cfg = config or MemberlistConfig()
        cfg.name = f"{name}.{region}"
        cfg.bind_host = bind_host
        cfg.bind_port = bind_port
        cfg.advertise_host = advertise_host
        if encrypt_key:
            cfg.encrypt_key = encrypt_key
        self.memberlist = Memberlist(cfg, self._tags)
        self.memberlist.on_join = self._on_change
        self.memberlist.on_update = self._on_change
        self.memberlist.on_leave = self._on_gone
        self.memberlist.on_fail = self._on_gone
        # fires (meta, status) whenever the server set changes, status one
        # of "alive" | "failed" | "left" — the reference's reconcileCh
        # consumer (leader.go:836 reconcileMember). The distinction
        # matters: only a graceful leave may shrink the raft peer set;
        # removing voters on failure suspicion invites split-brain.
        self.on_server_change: Optional[Callable[[ServerMeta, str], None]] = None
        self._ingest(self.memberlist.local_member())

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ServerMembership":
        self.memberlist.start()
        return self

    def join(self, seeds: List[Tuple[str, int]]) -> int:
        n = self.memberlist.join(seeds)
        # seed states arrive via the push-pull merge → _on_change hooks
        return n

    def leave(self) -> None:
        self.memberlist.leave()

    @property
    def gossip_addr(self) -> Tuple[str, int]:
        return self.memberlist.addr

    # -- leadership tag --------------------------------------------------

    def set_leader(self, is_leader: bool) -> None:
        with self._lock:
            want = "1" if is_leader else ""
            if self._tags.get("leader", "") == want:
                return
            if is_leader:
                self._tags["leader"] = "1"
            else:
                self._tags.pop("leader", None)
            tags = dict(self._tags)
        self.memberlist.set_tags(tags)
        self._ingest(self.memberlist.local_member())

    # -- queries ---------------------------------------------------------

    def regions(self) -> List[str]:
        with self._lock:
            return sorted(r for r, servers in self.peers.items() if servers)

    def servers_in_region(self, region: Optional[str] = None) -> List[ServerMeta]:
        with self._lock:
            return list(self.peers.get(region or self.region, {}).values())

    def leader_in_region(self, region: Optional[str] = None) -> Optional[ServerMeta]:
        for s in self.servers_in_region(region):
            if s.is_leader:
                return s
        return None

    def num_servers(self) -> int:
        return len(self.servers_in_region())

    def members(self) -> List[Member]:
        return self.memberlist.all_members()

    # -- membership hooks ------------------------------------------------

    def _ingest(self, member: Member) -> Optional[ServerMeta]:
        meta = _parse_server(member)
        if meta is None:
            return None
        with self._lock:
            self.peers.setdefault(meta.region, {})[meta.name] = meta
        return meta

    def _on_change(self, member: Member) -> None:
        meta = self._ingest(member)
        if meta is not None and self.on_server_change is not None:
            self.on_server_change(meta, "alive")

    def _on_gone(self, member: Member) -> None:
        from ..gossip.memberlist import STATUS_LEFT

        meta = _parse_server(member)
        if meta is None:
            return
        with self._lock:
            self.peers.get(meta.region, {}).pop(meta.name, None)
        if self.on_server_change is not None:
            status = "left" if member.status == STATUS_LEFT else "failed"
            self.on_server_change(meta, status)
