"""Periodic job dispatcher: cron-launches child jobs.

Fills the role of reference ``nomad/periodic.go`` (:22 PeriodicDispatch —
heap of next launch times, leader-only) plus the cron evaluation the
reference delegates to the vendored gorhill/cronexpr; here a small 5-field
cron engine (minute hour day-of-month month day-of-week, with ``*``, lists,
ranges, and ``*/step``) is implemented directly.

At each launch time the dispatcher derives a child job named
``<parent>/periodic-<unixtime>`` (reference periodic.go deriveJob) and
registers it through the normal Job.Register path, which creates the eval.
``prohibit_overlap`` skips a launch while a previous child is live
(periodic.go:ForceRun / shouldRun overlap check). Launches are recorded in
the state store (periodic_launch table, schema.go:31-49) so a new leader
resumes from the last launch instead of re-firing old ones.
"""
from __future__ import annotations

import calendar
import logging
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Optional, Tuple

from ..structs.structs import Job
from ..utils.lock_witness import witness_lock

# ---------------------------------------------------------------------------
# cron engine
# ---------------------------------------------------------------------------

_FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))


def _parse_field(spec: str, lo: int, hi: int) -> frozenset:
    """One cron field -> set of matching values. day-of-week: 0=Sunday,
    7 normalized to 0."""
    out = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise ValueError(f"cron step must be positive: {spec!r}")
        if part == "*" or part == "":
            lo_p, hi_p = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo_p, hi_p = int(a), int(b)
        else:
            lo_p = hi_p = int(part)
            if "/" in spec and step > 1:
                hi_p = hi  # "N/step" means starting at N
        for v in range(lo_p, hi_p + 1, step):
            if lo == 0 and hi == 6:  # day-of-week: 7 == Sunday == 0
                v = 0 if v == 7 else v
            if not (lo <= v <= hi):
                raise ValueError(f"cron value {v} out of range in {spec!r}")
            out.add(v)
    return frozenset(out)


class CronExpr:
    """A parsed 5-field cron expression."""

    def __init__(self, spec: str) -> None:
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec needs 5 fields, got {spec!r}")
        self.minutes, self.hours, self.doms, self.months, self.dows = (
            _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _FIELD_RANGES)
        )
        self.dom_restricted = fields[2] != "*"
        self.dow_restricted = fields[4] != "*"

    def _day_matches(self, dt: datetime) -> bool:
        dom_ok = dt.day in self.doms
        dow_ok = (dt.weekday() + 1) % 7 in self.dows  # python Mon=0 -> cron Sun=0
        # vixie-cron: if both dom and dow are restricted, either matches
        if self.dom_restricted and self.dow_restricted:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def next_after(self, after: datetime) -> Optional[datetime]:
        """Earliest instant strictly after ``after`` matching the spec."""
        dt = after.replace(second=0, microsecond=0) + timedelta(minutes=1)
        limit = after + timedelta(days=366 * 4 + 1)  # cover leap-day specs
        while dt <= limit:
            if dt.month not in self.months or not self._day_matches(dt):
                dt = (dt + timedelta(days=1)).replace(hour=0, minute=0)
                continue
            if dt.hour not in self.hours:
                dt = (dt + timedelta(hours=1)).replace(minute=0)
                continue
            if dt.minute not in self.minutes:
                dt += timedelta(minutes=1)
                continue
            return dt
        return None


def _tzinfo(name: str):
    if not name or name.upper() == "UTC":
        return timezone.utc
    from zoneinfo import ZoneInfo

    return ZoneInfo(name)


def next_launch_ns(job: Job, after_ns: int) -> Optional[int]:
    """Next launch time (ns) for a periodic job, strictly after ``after_ns``.
    The cron spec is evaluated on the wall clock of the job's configured
    timezone (reference periodic.go Next + GetTimeZone)."""
    p = job.periodic
    if p is None or not p.enabled:
        return None
    if p.spec_type != "cron":
        raise ValueError(f"unsupported periodic spec_type {p.spec_type!r}")
    tz = _tzinfo(p.timezone)
    after = datetime.fromtimestamp(after_ns / 1e9, tz=tz)
    nxt = CronExpr(p.spec).next_after(after)
    if nxt is None:
        return None
    return int(nxt.timestamp() * 1e9)


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------


class PeriodicDispatch:
    """Leader-only launcher of periodic jobs' children."""

    def __init__(self, server) -> None:
        self.server = server
        self.logger = logging.getLogger("nomad_tpu.periodic")
        self._lock = witness_lock("periodic.PeriodicDispatch._lock")
        self._cond = threading.Condition(self._lock)
        self.enabled = False
        self._generation = 0
        # (namespace, job id) -> (job, next launch ns)
        self.tracked: Dict[Tuple[str, str], Tuple[Job, Optional[int]]] = {}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            if enabled == self.enabled:
                return
            self.enabled = enabled
            self._generation += 1
            gen = self._generation
            if not enabled:
                self.tracked.clear()
                self._cond.notify_all()
                return
        self._restore()
        t = threading.Thread(target=self._run, args=(gen,), name="periodic", daemon=True)
        t.start()

    def _restore(self) -> None:
        """Track every periodic job, resuming from its recorded last launch;
        a launch missed while no leader was running fires immediately
        (reference leader.go:376 restorePeriodicDispatcher force-runs
        missed launches)."""
        state = self.server.fsm.state
        now = time.time_ns()
        for job in state.jobs():
            if not (job.is_periodic() and not job.stopped()):
                continue
            last = state.periodic_launch_by_id(job.namespace, job.id)
            if last:
                try:
                    missed = next_launch_ns(job, last)
                except ValueError:
                    continue
                if missed is not None and missed <= now:
                    try:
                        self.force_launch(job.namespace, job.id, missed)
                    except Exception:  # noqa: BLE001
                        self.logger.exception("catch-up launch of %s failed", job.id)
                        self._track(job, now)
                    continue
            self._track(job, max(last, now) if last else now)

    def add(self, job: Job) -> None:
        """Track (or update/untrack) a periodic job on registration
        (periodic.go:Add)."""
        with self._lock:
            if not self.enabled:
                return
        if not job.is_periodic() or job.stopped():
            self.remove(job.namespace, job.id)
            return
        self._track(job, time.time_ns())

    def _track(self, job: Job, after_ns: int) -> None:
        try:
            nxt = next_launch_ns(job, after_ns)
        except ValueError:
            self.logger.exception("invalid periodic spec for %s", job.id)
            return
        with self._lock:
            self.tracked[(job.namespace, job.id)] = (job, nxt)
            self._cond.notify_all()

    def remove(self, namespace: str, job_id: str) -> None:
        with self._lock:
            if self.tracked.pop((namespace, job_id), None) is not None:
                self._cond.notify_all()

    # ------------------------------------------------------------------

    def _run(self, gen: int) -> None:
        while True:
            with self._lock:
                if not self.enabled or self._generation != gen:
                    return
                now = time.time_ns()
                due = [
                    (key, job, nxt)
                    for key, (job, nxt) in self.tracked.items()
                    if nxt is not None and nxt <= now
                ]
                if not due:
                    nexts = [n for _, n in self.tracked.values() if n is not None]
                    wait_s = min(1.0, (min(nexts) - now) / 1e9) if nexts else 1.0
                    self._cond.wait(timeout=max(0.01, wait_s))
                    continue
            for key, job, launch_ns in due:
                try:
                    self.force_launch(job.namespace, job.id, launch_ns)
                except KeyError:
                    # job deregistered or no longer periodic: stop tracking
                    self.remove(*key)
                except Exception:  # noqa: BLE001
                    self.logger.exception("periodic launch of %s failed", job.id)
                    # advance (never resurrect a removed entry) so a bad job
                    # can't hot-loop the dispatcher
                    with self._lock:
                        if key in self.tracked:
                            still_job, _ = self.tracked[key]
                        else:
                            continue
                    self._track(still_job, launch_ns)

    def _children(self, namespace: str, parent_id: str) -> List[Job]:
        return self.server.fsm.state.jobs_by_parent(namespace, parent_id)

    def _child_live(self, child: Job) -> bool:
        """A child is live while it has a non-terminal alloc or an eval still
        in flight (the reference checks Job.Status == dead, which its state
        store recomputes from the same alloc/eval facts)."""
        state = self.server.fsm.state
        if child.stopped():
            return False
        if any(
            not a.terminal_status()
            for a in state.allocs_by_job(child.namespace, child.id, False)
        ):
            return True
        return any(
            not e.terminal_status()
            for e in state.evals_by_job(child.namespace, child.id)
        )

    def derive_job(self, parent: Job, launch_ns: int) -> Job:
        """Child job named <parent>/periodic-<unixtime> (periodic.go deriveJob)."""
        return parent.derive_child(f"{parent.id}/periodic-{launch_ns // 10**9}")

    def force_launch(
        self, namespace: str, job_id: str, launch_ns: Optional[int] = None
    ) -> Optional[str]:
        """Launch one child now (Periodic.Force RPC / scheduled launch).
        Returns the child job id, or None when skipped for overlap."""
        state = self.server.fsm.state
        job = state.job_by_id(namespace, job_id)
        if job is None or not job.is_periodic():
            raise KeyError(f"{job_id} is not a periodic job")
        launch_ns = launch_ns or time.time_ns()
        self._track(job, launch_ns)  # schedule the following launch

        if job.periodic.prohibit_overlap and any(
            self._child_live(c) for c in self._children(namespace, job_id)
        ):
            self.logger.info("skipping launch of %s: previous child live", job_id)
            return None
        child = self.derive_job(job, launch_ns)
        # register first: a failed registration must leave the slot
        # unconsumed so the launch retries rather than silently vanishing
        # (a dup after a crash between the two applies is caught by the
        # overlap check / child id equality)
        self.server.register_job(child)
        self.server.raft_apply("periodic-launch", (namespace, job_id, launch_ns))
        return child.id
