"""Plan queue and plan applier: the cluster's serialization point.

Semantics follow reference ``nomad/plan_queue.go`` and ``nomad/plan_apply.go``:
workers submit plans optimistically; the leader's single applier thread
re-validates every touched node against current state (AllocsFit,
plan_apply.go:628), partially commits what fits, and returns a RefreshIndex
forcing stale workers to re-plan. The per-node feasibility fan-out the
reference does over a goroutine pool (plan_apply_pool.go) is a vectorized
batch here — the same capacity math the TPU engine runs, host-side.
"""
from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from ..structs.funcs import allocs_fit, remove_allocs
from ..utils import metrics
from ..structs.structs import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_PREEMPTION,
    Allocation,
    Evaluation,
    Plan,
    PlanResult,
)
from .fsm import APPLY_PLAN_RESULTS


class PendingPlan:
    def __init__(self, plan: Plan) -> None:
        self.plan = plan
        self.future: Future = Future()


class PlanQueue:
    """Leader-only priority queue of submitted plans (reference plan_queue.go)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._counter = itertools.count()
        self.enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            prev = self.enabled
            self.enabled = enabled
            if prev and not enabled:
                for _, _, pending in self._heap:
                    pending.future.set_exception(RuntimeError("plan queue disabled"))
                self._heap.clear()
            self._cond.notify_all()

    def enqueue(self, plan: Plan) -> PendingPlan:
        with self._lock:
            if not self.enabled:
                raise RuntimeError("plan queue is disabled")
            pending = PendingPlan(plan)
            heapq.heappush(self._heap, (-plan.priority, next(self._counter), pending))
            self._cond.notify()
            return pending

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        with self._cond:
            if not self._heap:
                self._cond.wait(timeout=timeout)
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"depth": len(self._heap)}


class Planner:
    """The leader's plan applier loop (reference planner.planApply)."""

    def __init__(self, raft, peer: int, fsm, plan_queue: PlanQueue, logger=None) -> None:
        self.raft = raft
        self.peer = peer
        self.fsm = fsm
        self.plan_queue = plan_queue
        self.logger = logger or logging.getLogger("nomad_tpu.planner")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="plan-apply", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.plan_queue.dequeue(timeout=0.2)
            if pending is None:
                continue
            metrics.set_gauge("nomad.plan.queue_depth", self.plan_queue.stats().get("depth", 0))
            try:
                start = metrics.now()
                result = self.apply_plan(pending.plan)
                metrics.measure_since("nomad.plan.apply", start)
                pending.future.set_result(result)
            except Exception as e:  # noqa: BLE001 — worker gets the error
                self.logger.exception("plan apply failed")
                pending.future.set_exception(e)

    # ------------------------------------------------------------------

    def evaluate_plan(self, snapshot, plan: Plan) -> PlanResult:
        """Re-check every touched node against current state; keep what fits
        (reference plan_apply.go:399/:436/:628)."""
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation={},
            node_preemptions={},
            deployment=plan.deployment,
            deployment_updates=list(plan.deployment_updates),
        )
        partial = False
        for node_id, allocs in plan.node_allocation.items():
            ok = self._evaluate_node_plan(snapshot, plan, node_id)
            if ok:
                result.node_allocation[node_id] = allocs
                if node_id in plan.node_preemptions:
                    result.node_preemptions[node_id] = plan.node_preemptions[node_id]
            else:
                partial = True
        if partial:
            # Invalid placements: cancel deployment bits if everything failed
            if not result.node_allocation:
                result.deployment = None
                result.deployment_updates = []
            result.refresh_index = self.fsm.state.latest_index
        return result

    def _evaluate_node_plan(self, snapshot, plan: Plan, node_id: str) -> bool:
        new_allocs = plan.node_allocation.get(node_id, [])
        node = snapshot.node_by_id(node_id)
        if node is None:
            return not new_allocs
        if node.drain or not node.ready():
            return False

        existing = snapshot.allocs_by_node(node_id)
        existing = [a for a in existing if not a.terminal_status()]
        # Remove planned evictions, preemptions, AND prior versions of the
        # planned allocations (in-place updates must not double count).
        remove = list(plan.node_update.get(node_id, []))
        remove.extend(plan.node_preemptions.get(node_id, []))
        remove.extend(new_allocs)
        if remove:
            existing = remove_allocs(existing, remove)
        proposed = existing + new_allocs

        fit, reason, _util = allocs_fit(node, proposed, None, check_devices=True)
        if not fit:
            self.logger.debug("plan for node %s rejected: %s", node_id, reason)
        return fit

    def apply_plan(self, plan: Plan) -> PlanResult:
        snapshot = self.fsm.state.snapshot()
        start = metrics.now()
        result = self.evaluate_plan(snapshot, plan)
        metrics.measure_since("nomad.plan.evaluate", start)
        if result.is_noop():
            return result

        # Flatten + stamp, attaching the plan's job (the same struct-sharing
        # the reference relies on in UpsertPlanResults).
        alloc_updates: List[Allocation] = []
        for allocs in result.node_allocation.values():
            for alloc in allocs:
                existing = snapshot.alloc_by_id(alloc.id)
                alloc.create_index = existing.create_index if existing else 0
                if alloc.job is None:
                    alloc.job = plan.job
                alloc_updates.append(alloc)
        allocs_stopped: List[Allocation] = []
        for allocs in result.node_update.values():
            allocs_stopped.extend(allocs)
        allocs_preempted: List[Allocation] = []
        preemption_evals: List[Evaluation] = []
        preempted_job_ids = set()
        for allocs in result.node_preemptions.values():
            for alloc in allocs:
                allocs_preempted.append(alloc)
                existing = snapshot.alloc_by_id(alloc.id)
                if existing is not None:
                    preempted_job_ids.add((existing.namespace, existing.job_id))
        for namespace, job_id in preempted_job_ids:
            job = snapshot.job_by_id(namespace, job_id)
            if job is None:
                continue
            preemption_evals.append(
                Evaluation(
                    namespace=namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EVAL_TRIGGER_PREEMPTION,
                    job_id=job_id,
                    status=EVAL_STATUS_PENDING,
                )
            )

        payload = {
            "alloc_updates": alloc_updates,
            "allocs_stopped": allocs_stopped,
            "allocs_preempted": allocs_preempted,
            "deployment": result.deployment,
            "deployment_updates": result.deployment_updates,
            "eval_id": plan.eval_id,
            "preemption_evals": preemption_evals,
            # stamped pre-apply so every replica arms identical deployment
            # progress deadlines
            "timestamp_ns": time.time_ns(),
        }
        index, _ = self.raft.apply(self.peer, APPLY_PLAN_RESULTS, payload)
        result.alloc_index = index

        # Stamp result allocs (the scheduler checks create==modify for "new")
        for alloc in alloc_updates:
            stored = self.fsm.state.alloc_by_id(alloc.id)
            if stored is not None:
                alloc.create_index = stored.create_index
                alloc.modify_index = stored.modify_index
        return result
