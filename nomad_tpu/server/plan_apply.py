"""Plan queue and plan applier: the cluster's serialization point.

Semantics follow reference ``nomad/plan_queue.go`` and ``nomad/plan_apply.go``:
workers submit plans optimistically; the leader's single applier thread
re-validates every touched node against current state (AllocsFit,
plan_apply.go:628), partially commits what fits, and returns a RefreshIndex
forcing stale workers to re-plan.

Two of the reference's throughput mechanisms are reproduced here:

* **Pipelined commit** (plan_apply.go:45–70): while plan N's raft apply is
  in flight, plan N+1 is evaluated against an OPTIMISTIC snapshot that
  already includes N's results. Before dispatching N+1's apply we wait for
  N to commit; the worker's response is delivered asynchronously from the
  apply waiter, so the applier thread is never parked on raft latency
  while work is queued.
* **Batched node re-check**: the per-node feasibility fan-out the
  reference does over a goroutine pool (plan_apply_pool.go) is one
  numpy pass here — every touched node's cpu/mem/disk totals vs proposed
  usage compare at once; only nodes that pass capacity run the discrete
  port-collision / device host checks.
"""
from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..chaos.injector import fire as chaos_fire
from ..structs.funcs import remove_allocs
from ..structs.network import NetworkIndex
from ..trace import lifecycle as _lifecycle
from ..utils import metrics, phases
from ..structs.structs import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_PREEMPTION,
    Allocation,
    Evaluation,
    Plan,
    PlanResult,
)
from .fsm import APPLY_PLAN_RESULTS, APPLY_PLAN_RESULTS_BATCH  # noqa: F401 — single-plan op kept for wire compat
from ..utils.lock_witness import witness_lock


class PendingPlan:
    def __init__(self, plan: Plan) -> None:
        self.plan = plan
        self.future: Future = Future()


class PlanQueue:
    """Leader-only priority queue of submitted plans (reference plan_queue.go)."""

    def __init__(self) -> None:
        self._lock = witness_lock("plan_apply.PlanQueue._lock")
        self._cond = threading.Condition(self._lock)
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._counter = itertools.count()
        self.enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            prev = self.enabled
            self.enabled = enabled
            if prev and not enabled:
                for _, _, pending in self._heap:
                    pending.future.set_exception(RuntimeError("plan queue disabled"))
                self._heap.clear()
            self._cond.notify_all()

    def enqueue(self, plan: Plan) -> PendingPlan:
        with self._lock:
            if not self.enabled:
                raise RuntimeError("plan queue is disabled")
            pending = PendingPlan(plan)
            heapq.heappush(self._heap, (-plan.priority, next(self._counter), pending))
            self._cond.notify()
            return pending

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._heap:
                if not self.enabled:
                    return None
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(timeout=remaining)
            return heapq.heappop(self._heap)[2]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"depth": len(self._heap)}


class Planner:
    """The leader's plan applier loop (reference planner.planApply)."""

    def __init__(self, raft, peer: int, fsm, plan_queue: PlanQueue, logger=None,
                 batch_max: int = 32) -> None:
        self.raft = raft
        self.peer = peer
        self.fsm = fsm
        self.plan_queue = plan_queue
        self.logger = logger or logging.getLogger("nomad_tpu.planner")
        # max queued plans grouped into one raft entry (see _run)
        self.batch_max = max(1, int(batch_max))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="plan-apply", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        # Pipelined applier (plan_apply.go:45–70): track one outstanding
        # raft apply (apply_future resolves to its committed index, 0 on
        # failure) and an optimistic snapshot that already includes it.
        #
        # Snapshot retention: taking a fresh snapshot per plan is O(store)
        # and was the drain bottleneck at C1M rates. The store's
        # capacity_epoch counts every capacity-relevant write (nodes,
        # allocs, dense blocks, jobs); as long as the live epoch equals
        # our prediction (snapshot epoch + our own dispatched applies),
        # the only writes that landed since are eval-status noise and the
        # retained optimistic snapshot is capacity-identical to committed
        # state — index staleness checks may be bypassed safely.
        apply_future: Optional[Future] = None
        snap = None
        prev_plan_result_index = 0
        expected_epoch: Optional[int] = None

        def epoch_current() -> bool:
            live = self.fsm.state
            return (
                snap is not None
                and expected_epoch is not None
                and getattr(snap, "store_id", None) == live.store_id
                and live.capacity_epoch == expected_epoch
            )

        carry: List[PendingPlan] = []
        while not self._stop.is_set():
            if carry:
                batch = carry
                carry = []
            else:
                first = self.plan_queue.dequeue(timeout=0.2)
                if first is None:
                    continue
                batch = [first]
            # Greedy batch gather: at C1M commit rates the per-plan
            # round trip (waiter thread, raft dispatch, FSM lock) is the
            # drain bottleneck, so queued plans are grouped into ONE
            # raft entry (APPLY_PLAN_RESULTS_BATCH). Each plan is still
            # evaluated sequentially against a snapshot containing its
            # predecessors' folds, so per-plan semantics are unchanged
            # (reference serialization point: plan_apply.go:45–70).
            while len(batch) < self.batch_max:
                nxt = self.plan_queue.dequeue(timeout=0)
                if nxt is None:
                    break
                batch.append(nxt)
            metrics.set_gauge("nomad.plan.queue_depth", self.plan_queue.stats().get("depth", 0))
            try:
                # Previous batch committed during dequeue? Keep the
                # optimistic view only if the commit was exactly what we
                # predicted (no interleaved capacity writes).
                if apply_future is not None and apply_future.done():
                    idx = self._future_index(apply_future)
                    prev_plan_result_index = max(prev_plan_result_index, idx)
                    apply_future = None
                    if idx == 0 or not epoch_current():
                        snap = None
                        expected_epoch = None

                min_index = max(
                    [prev_plan_result_index]
                    + [p.plan.snapshot_index for p in batch]
                )
                # Retention invariant: a retained snapshot is capacity-
                # identical to committed state iff epoch_current(). With
                # no apply in flight there is no post-wait re-evaluation
                # to correct a bad evaluation, so ANY epoch mismatch must
                # discard the snapshot outright — independent of index
                # staleness (the mismatch means a foreign capacity write
                # landed: node drain/down, client sync, eval-GC delete).
                # With an apply in flight the mismatch may just be our own
                # uncommitted delta; keep the optimistic view unless it is
                # also index-stale, and rely on the post-wait re-check.
                if snap is not None and not epoch_current():
                    if apply_future is None or snap.latest_index < min_index:
                        snap = None
                        expected_epoch = None
                # Does the evaluation snapshot include the in-flight batch's
                # results? Only the retained optimistic snapshot does; a
                # fresh snapshot taken while an apply is still in flight
                # may lack them, and an evaluation against it cannot be
                # trusted not to double-commit the same capacity.
                saw_inflight = True
                if snap is None:
                    snap = self._snapshot_min_index(min_index)
                    expected_epoch = snap.capacity_epoch
                    saw_inflight = apply_future is None

                items, batch_delta, snap_ok, leftovers = (
                    self._evaluate_and_fold(batch, snap)
                )
                carry = leftovers

                # Ensure any parallel apply completed before dispatching
                # the next one (bounds how stale the optimism can get).
                if apply_future is not None:
                    idx = self._future_index(apply_future, wait=True)
                    prev_plan_result_index = max(prev_plan_result_index, idx)
                    apply_future = None
                    if idx == 0 or not saw_inflight or not epoch_current():
                        # Re-validate against committed state whenever the
                        # evaluations could not be trusted: they ran blind
                        # to the in-flight batch, or a failed apply
                        # (idx == 0) never delivered its optimism, or a
                        # foreign capacity write (node drain, client sync)
                        # interleaved with the retained snapshot —
                        # dispatching unchecked in any of these would
                        # commit placements against capacity state that
                        # never existed.
                        snap = self._snapshot_min_index(
                            max(prev_plan_result_index, min_index)
                        )
                        expected_epoch = snap.capacity_epoch
                        redo = [it[0] for it in items]
                        items, batch_delta, snap_ok, leftovers = (
                            self._evaluate_and_fold(redo, snap)
                        )
                        carry = leftovers + carry

                if not items:
                    if not snap_ok:
                        snap = None
                        expected_epoch = None
                    continue
                apply_future = self._dispatch_batch(items)
                if expected_epoch is not None:
                    expected_epoch += batch_delta
                if not snap_ok:
                    # an optimistic fold-in failed partway: the snapshot
                    # is inconsistent — never evaluate against it again
                    snap = None
                    expected_epoch = None
            except Exception as e:  # noqa: BLE001 — workers get the error
                self.logger.exception("plan apply failed")
                for pending in batch:
                    if not pending.future.done():
                        pending.future.set_exception(e)
                carry = []

        if apply_future is not None:
            apply_future.result()

    @staticmethod
    def _future_index(future: Future, wait: bool = False) -> int:
        try:
            return future.result() if wait else future.result(timeout=0)
        except Exception:  # noqa: BLE001 — failed apply: index unknown
            return 0

    def _snapshot_min_index(self, min_index: int):
        start = metrics.now()
        snap = self.fsm.state.snapshot_min_index(min_index)
        metrics.measure_since("nomad.plan.wait_for_index", start)
        return snap

    # ------------------------------------------------------------------

    def evaluate_plan(self, snapshot, plan: Plan) -> PlanResult:
        """Re-check every touched node against current state; keep what fits
        (reference plan_apply.go:399/:436/:628).

        The capacity math for ALL touched nodes runs as one numpy batch
        (the vectorized analog of plan_apply_pool.go's goroutine fan-out);
        only nodes that pass capacity run the discrete port-collision and
        device checks host-side."""
        # chaos hook: a fault here is THIS plan's failure only — the
        # batched waiter's per-payload isolation resolves this plan's
        # future with the error while its batch-mates commit normally
        chaos_fire("plan_apply", eval_id=getattr(plan, "eval_id", None))
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation={},
            node_preemptions={},
            deployment=plan.deployment,
            deployment_updates=list(plan.deployment_updates),
        )
        partial = False

        node_ids: List[str] = []
        proposed_by_node: List[Optional[List[Allocation]]] = []
        nodes = []
        for node_id in plan.node_allocation:
            new_allocs = plan.node_allocation[node_id]
            node = snapshot.node_by_id(node_id)
            if node is None:
                if new_allocs:
                    partial = True
                continue
            if node.drain or not node.ready():
                partial = True
                continue
            existing = snapshot.allocs_by_node(node_id)
            existing = [a for a in existing if not a.terminal_status()]
            # Remove planned evictions, preemptions, AND prior versions of
            # the planned allocations (in-place updates must not double
            # count).
            remove = list(plan.node_update.get(node_id, []))
            remove.extend(plan.node_preemptions.get(node_id, []))
            remove.extend(new_allocs)
            if remove:
                existing = remove_allocs(existing, remove)
            node_ids.append(node_id)
            nodes.append(node)
            proposed_by_node.append(existing + new_allocs)

        fit_mask = self._batch_capacity_check(nodes, proposed_by_node)

        for i, node_id in enumerate(node_ids):
            ok = bool(fit_mask[i])
            if ok:
                ok = self._node_discrete_checks(nodes[i], proposed_by_node[i])
            if ok:
                result.node_allocation[node_id] = plan.node_allocation[node_id]
                if node_id in plan.node_preemptions:
                    result.node_preemptions[node_id] = plan.node_preemptions[node_id]
            else:
                self.logger.debug("plan for node %s rejected", node_id)
                partial = True

        if plan.dense_placements:
            dense_out, dense_partial = self._evaluate_dense(snapshot, plan, result)
            result.dense_placements = dense_out
            if dense_partial:
                partial = True

        if partial:
            # Invalid placements: cancel deployment bits if everything failed
            if not result.node_allocation and not result.dense_placements:
                result.deployment = None
                result.deployment_updates = []
            # COMMITTED state only: an optimistic (uncommitted) index here
            # could strand the re-planning worker waiting for an index that
            # never lands if the in-flight apply fails. For dispatched
            # plans the apply waiter raises this to the real alloc_index.
            result.refresh_index = self.fsm.state.latest_index
        return result

    def _evaluate_dense(self, snapshot, plan: Plan, result: PlanResult):
        """Re-check dense placement blocks against current state without
        materializing a single Allocation: per touched node, committed
        usage comes from the state store's incremental mirror, this
        plan's stops/preemptions subtract, and each block's placements
        add count x ask_vec. Per-node all-or-nothing, like the object
        path's evaluateNodePlan (reference plan_apply.go:628).

        Returns (committed_blocks, partial)."""
        from ..structs.funcs import alloc_usage_vec

        # capacity this plan's committed stops/preemptions free per node
        freed: Dict[str, List[float]] = {}

        def _free(alloc) -> None:
            base = snapshot.alloc_by_id(alloc.id)
            if base is None or base.terminal_status():
                return
            u = alloc_usage_vec(base)
            row = freed.setdefault(base.node_id, [0.0, 0.0, 0.0, 0.0])
            for d in range(4):
                row[d] += u[d]

        for allocs in result.node_update.values():
            for alloc in allocs:
                _free(alloc)
        for allocs in result.node_preemptions.values():
            for alloc in allocs:
                _free(alloc)

        # Dense-path preemptions: plan.node_preemptions rows for nodes the
        # object path never touched are credited (and later committed)
        # here. Object-path nodes were already folded above — accepted ones
        # are in result.node_preemptions, rejected ones must stay dropped.
        dense_pre: Dict[str, list] = {
            nid: allocs
            for nid, allocs in plan.node_preemptions.items()
            if allocs and nid not in plan.node_allocation
        }
        for allocs in dense_pre.values():
            for alloc in allocs:
                _free(alloc)

        mirror = getattr(snapshot, "_node_usage", {})
        # adds accumulated across blocks (and the object-path placements
        # committed above, which the mirror does not include yet)
        pending: Dict[str, List[float]] = {}
        for allocs in result.node_allocation.values():
            for alloc in allocs:
                if alloc.terminal_status():
                    continue
                u = alloc_usage_vec(alloc)
                row = pending.setdefault(alloc.node_id, [0.0, 0.0, 0.0, 0.0])
                base = snapshot.alloc_by_id(alloc.id)
                for d in range(4):
                    row[d] += u[d]
                if base is not None and not base.terminal_status():
                    bu = alloc_usage_vec(base)
                    for d in range(4):
                        row[d] -= bu[d]

        # Per-node ALL-OR-NOTHING across the WHOLE plan (the object
        # path's evaluateNodePlan semantics): aggregate every block's
        # asks per node first, check each node once against the combined
        # addition, then trim every block by the failing-node set. Every
        # per-placement step here is vectorized numpy over the blocks'
        # parallel arrays — the evaluate stage of the eval-lifecycle
        # pipeline shares one interpreter with encode/apply, so a Python
        # loop over 1M placements would serialize the whole pipeline.
        zero4 = (0.0, 0.0, 0.0, 0.0)
        # freed/pending are empty for pure dense plans (the C1M commit
        # shape): skip their lookups entirely on that path
        has_adj = bool(freed) or bool(pending)

        blocks = plan.dense_placements
        id_arrs = [np.asarray(b.node_ids) for b in blocks]
        counts = np.array([a.shape[0] for a in id_arrs], np.int64)
        offs = np.zeros(len(blocks) + 1, np.int64)
        np.cumsum(counts, out=offs[1:])
        all_ids = np.concatenate(id_arrs)
        # inv maps placement row -> unique-node row; the per-node added
        # load is one scatter-add of count x ask_vec
        uids, inv = np.unique(all_ids, return_inverse=True)
        asks = np.repeat(
            np.array([b.ask_vec for b in blocks], np.float64).reshape(-1, 4),
            counts, axis=0,
        )
        k = int(uids.shape[0])
        add = np.zeros((k, 4), np.float64)
        np.add.at(add, inv, asks)

        from ..structs.funcs import node_capacity_vecs

        # per-unique-node rows: node objects live behind Python dicts, so
        # this loop is O(touched nodes), not O(placements) — the capacity
        # vecs are memoized per node (structs.funcs)
        totals = np.zeros((k, 4), np.float64)
        res = np.zeros((k, 4), np.float64)
        used = np.zeros((k, 4), np.float64)
        adj = np.zeros((k, 4), np.float64) if has_adj else None
        alive = np.ones(k, bool)
        nodes_tbl = snapshot.nodes_table
        for i in range(k):
            node_id = uids[i]
            node = nodes_tbl.get(node_id)
            if node is None or node.drain or not node.ready():
                alive[i] = False
                continue
            totals[i], res[i] = node_capacity_vecs(node)
            used[i] = mirror.get(node_id, zero4)
            if has_adj:
                fr = freed.get(node_id, zero4)
                pend = pending.get(node_id, zero4)
                adj[i] = (pend[0] - fr[0], pend[1] - fr[1],
                          pend[2] - fr[2], pend[3] - fr[3])

        load = used + res + add if not has_adj else used + adj + res + add
        ok = alive & np.all(load <= totals, axis=1)
        bad_mask = ~ok

        out = []
        partial = bool(bad_mask.any())
        if partial:
            metrics.incr_counter(
                "nomad.plan.dense_nodes_rejected", int(bad_mask.sum())
            )
            if self.logger.isEnabledFor(logging.DEBUG):
                for i in np.nonzero(bad_mask & alive)[0]:
                    self.logger.debug(
                        "dense re-check rejected node %s: used=%s add=%s totals=%s",
                        str(uids[i])[:8], used[i], add[i], totals[i],
                    )
        # Commit dense-node preemptions only when the node's dense
        # placements survived (per-node all-or-nothing, same as the
        # object path: a rejected node keeps its victims running).
        if dense_pre:
            uid_ok = {str(uids[i]): bool(ok[i]) for i in range(k)}
            for nid, allocs in dense_pre.items():
                if uid_ok.get(nid):
                    result.node_preemptions[nid] = allocs
        for bi, block in enumerate(blocks):
            if not partial:
                out.append(block)
                continue
            bmask = bad_mask[inv[offs[bi]:offs[bi + 1]]]
            if not bmask.any():
                out.append(block)
                continue
            keep = np.nonzero(~bmask)[0]
            if keep.size:
                out.append(block.select([int(x) for x in keep]))
        return out, partial

    @staticmethod
    def _batch_capacity_check(nodes, proposed_by_node) -> np.ndarray:
        """One vectorized cpu/mem/disk superset check over all touched
        nodes (the math of funcs.allocs_fit/ComparableResources.superset,
        columnized). Returns a [M] bool mask."""
        m = len(nodes)
        if m == 0:
            return np.zeros(0, bool)
        totals = np.zeros((m, 3), np.float64)
        used = np.zeros((m, 3), np.float64)
        for i, node in enumerate(nodes):
            nr = node.node_resources
            totals[i, 0] = nr.cpu_shares
            totals[i, 1] = nr.memory_mb
            totals[i, 2] = nr.disk_mb
            rr = node.reserved_resources
            if rr is not None:
                used[i, 0] += rr.cpu_shares
                used[i, 1] += rr.memory_mb
                used[i, 2] += rr.disk_mb
            for alloc in proposed_by_node[i]:
                if alloc.terminal_status():
                    continue
                cr = alloc.comparable_resources()
                used[i, 0] += cr.flattened.cpu_shares
                used[i, 1] += cr.flattened.memory_mb
                used[i, 2] += cr.shared.disk_mb
        return np.all(used <= totals, axis=1)

    @staticmethod
    def _node_discrete_checks(node, proposed) -> bool:
        """Port-collision / per-device-bandwidth / device-count checks —
        the parts of allocs_fit that are discrete structures, run only for
        nodes that passed the batched capacity check and only when the
        proposed set actually uses networks/devices."""
        has_networks = False
        has_devices = False
        for alloc in proposed:
            ar = alloc.allocated_resources
            if ar is None:
                continue
            if ar.shared.networks:
                has_networks = True
            for tr in ar.tasks.values():
                if tr.networks:
                    has_networks = True
                if getattr(tr, "devices", None):
                    has_devices = True
        if has_networks:
            net_idx = NetworkIndex()
            if net_idx.set_node(node) or net_idx.add_allocs(proposed):
                return False
            if net_idx.overcommitted():
                return False
        if has_devices:
            from ..structs.devices import DeviceAccounter

            accounter = DeviceAccounter(node)
            if accounter.add_allocs(proposed):
                return False
        return True

    def _build_payload(self, snapshot, plan: Plan, result: PlanResult) -> dict:
        """Flatten + stamp, attaching the plan's job (the same struct-sharing
        the reference relies on in UpsertPlanResults)."""
        alloc_updates: List[Allocation] = []
        for allocs in result.node_allocation.values():
            for alloc in allocs:
                existing = snapshot.alloc_by_id(alloc.id)
                alloc.create_index = existing.create_index if existing else 0
                if alloc.job is None:
                    alloc.job = plan.job
                alloc_updates.append(alloc)
        allocs_stopped: List[Allocation] = []
        for allocs in result.node_update.values():
            allocs_stopped.extend(allocs)
        allocs_preempted: List[Allocation] = []
        preemption_evals: List[Evaluation] = []
        preempted_job_ids = set()
        for allocs in result.node_preemptions.values():
            for alloc in allocs:
                allocs_preempted.append(alloc)
                existing = snapshot.alloc_by_id(alloc.id)
                if existing is not None:
                    preempted_job_ids.add((existing.namespace, existing.job_id))
        for namespace, job_id in preempted_job_ids:
            job = snapshot.job_by_id(namespace, job_id)
            if job is None:
                continue
            preemption_evals.append(
                Evaluation(
                    namespace=namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EVAL_TRIGGER_PREEMPTION,
                    job_id=job_id,
                    status=EVAL_STATUS_PENDING,
                )
            )

        return {
            "alloc_updates": alloc_updates,
            "allocs_stopped": allocs_stopped,
            "allocs_preempted": allocs_preempted,
            # dense blocks ride the raft payload as-is (parallel arrays;
            # the FSM upserts them without materializing allocs)
            "dense_placements": result.dense_placements,
            "deployment": result.deployment,
            "deployment_updates": result.deployment_updates,
            "eval_id": plan.eval_id,
            "preemption_evals": preemption_evals,
            # stamped pre-apply so every replica arms identical deployment
            # progress deadlines
            "timestamp_ns": time.time_ns(),
        }

    def _evaluate_and_fold(self, batch: List[PendingPlan], snap):
        """Evaluate each queued plan against ``snap``, folding every
        non-noop result in so plan k+1 sees plan k's expected outcome
        (the pipelined optimism of plan_apply.go:45–70, applied within a
        batch). Noop results are responded immediately. Returns
        (items, capacity_delta, snap_ok, leftovers): ``items`` is the
        list of (pending, result, payload) to commit as one raft entry;
        ``capacity_delta`` predicts the epoch bumps their FSM apply will
        perform; ``snap_ok`` False means a fold failed and the snapshot
        must be discarded after dispatch — the un-evaluated remainder of
        the batch is handed back as ``leftovers``."""
        items: List[Tuple[PendingPlan, PlanResult, dict]] = []
        delta_total = 0
        snap_ok = True
        leftovers: List[PendingPlan] = []
        for bi, pending in enumerate(batch):
            try:
                start = metrics.now()
                with phases.track("plan_evaluate"), \
                        _lifecycle.pipeline_stage("evaluate",
                                                  pending.plan.eval_id):
                    result = self.evaluate_plan(snap, pending.plan)
                metrics.measure_since("nomad.plan.evaluate", start)
                if result.is_noop():
                    _lifecycle.on_apply(pending.plan.eval_id)
                    pending.future.set_result(result)
                    continue
                payload = self._build_payload(snap, pending.plan, result)
                # one bump for the combined object-alloc upsert (when
                # non-empty) plus one per dense block
                # (state_store.upsert_plan_results)
                delta = len(payload["dense_placements"])
                if (
                    payload["alloc_updates"] or payload["allocs_stopped"]
                    or payload["allocs_preempted"]
                ):
                    delta += 1
                if not self._fold_optimistic(snap, payload):
                    # a half-mutated snapshot cannot host further
                    # evaluations: commit what we have, re-run the rest
                    # of the batch on a fresh snapshot next iteration
                    snap_ok = False
                    delta_total += delta
                    items.append((pending, result, payload))
                    leftovers = list(batch[bi + 1:])
                    break
                delta_total += delta
                items.append((pending, result, payload))
            except Exception as e:  # noqa: BLE001 — isolate to this plan
                self.logger.exception("plan evaluation failed")
                if not pending.future.done():
                    pending.future.set_exception(e)
        return items, delta_total, snap_ok, leftovers

    def _fold_optimistic(self, snap, payload: dict) -> bool:
        """Optimistic application to the applier's private snapshot: the
        raft log is the pessimistic truth; this view lets the next plan
        verify against this one's expected outcome during apply latency.
        Returns False when the fold failed (snapshot must be discarded)."""
        guess_index = self.fsm.state.latest_index + 1
        try:
            # deployment COPIED: the store keeps (and index-stamps) the
            # object it is given, and this one is also headed into the
            # real FSM via raft — sharing it would alias two state stores
            # to one mutable instance across threads
            deployment = payload["deployment"]
            snap.upsert_plan_results(
                guess_index,
                alloc_updates=payload["alloc_updates"],
                allocs_stopped=payload["allocs_stopped"],
                allocs_preempted=payload["allocs_preempted"],
                # dense blocks CLONED for the same aliasing reason: the
                # in-proc raft hands the payload's block objects straight
                # to the FSM store, whose commit stamp must not race with
                # snapshot readers materializing against our provisional
                # guess-index stamp
                dense_placements=[
                    b.clone_for_snapshot()
                    for b in payload["dense_placements"]
                ],
                deployment=deployment.copy() if deployment is not None else None,
                deployment_updates=payload["deployment_updates"],
                eval_id=payload["eval_id"],
                timestamp_ns=payload["timestamp_ns"],
            )
            return True
        except Exception:  # noqa: BLE001 — optimism only; raft is truth,
            # but a half-mutated snapshot must not be reused
            self.logger.exception("optimistic snapshot apply failed")
            return False

    def _dispatch_batch(self, items: List[Tuple[PendingPlan, PlanResult, dict]]) -> Future:
        """Fire ONE raft apply for the whole batch (plan_apply.go
        applyPlan + asyncPlanWait, batched): respond to every waiting
        worker from the apply waiter; the returned future resolves to
        the committed index (0 on failure)."""
        payloads = [payload for _, _, payload in items]
        index_future: Future = Future()

        def waiter() -> None:
            try:
                start = metrics.now()
                commit_t0 = _lifecycle.pipeline_now()
                with phases.track("raft_fsm"):
                    index, errors = self.raft.apply(
                        self.peer, APPLY_PLAN_RESULTS_BATCH, payloads
                    )
                metrics.measure_since("nomad.plan.apply", start)
                commit_t1 = _lifecycle.pipeline_now()
                for i, (pending, result, payload) in enumerate(items):
                    # one commit-stage span per wave in the batched entry
                    _lifecycle.pipeline_record(
                        "commit", payload["eval_id"], commit_t0, commit_t1
                    )
                    # per-payload isolation (fsm._apply_plan_results_batch):
                    # a failed payload must not be reported as committed,
                    # and committed ones must not be reported as failed
                    err = errors[i] if isinstance(errors, list) else None
                    if err is not None:
                        pending.future.set_exception(
                            RuntimeError(f"plan apply failed in FSM: {err}")
                        )
                        continue
                    result.alloc_index = index
                    if result.refresh_index:
                        result.refresh_index = max(result.refresh_index, index)
                    # Stamp result allocs (the scheduler checks
                    # create==modify for "new")
                    for alloc in payload["alloc_updates"]:
                        stored = self.fsm.state.alloc_by_id(alloc.id)
                        if stored is not None:
                            alloc.create_index = stored.create_index
                            alloc.modify_index = stored.modify_index
                    _lifecycle.on_apply(payload["eval_id"])
                    pending.future.set_result(result)
                index_future.set_result(index)
            except Exception as e:  # noqa: BLE001
                self.logger.exception("raft apply of plan batch failed")
                for pending, _, _ in items:
                    if not pending.future.done():
                        pending.future.set_exception(e)
                index_future.set_result(0)

        threading.Thread(target=waiter, name="plan-apply-wait", daemon=True).start()
        return index_future

    def apply_plan(self, plan: Plan) -> PlanResult:
        """Synchronous evaluate+apply (tests / direct callers); the
        pipelined loop in _run is the production path."""
        snapshot = self.fsm.state.snapshot()
        start = metrics.now()
        result = self.evaluate_plan(snapshot, plan)
        metrics.measure_since("nomad.plan.evaluate", start)
        if result.is_noop():
            return result
        pending = PendingPlan(plan)
        payload = self._build_payload(snapshot, plan, result)
        self._fold_optimistic(snapshot, payload)
        self._dispatch_batch([(pending, result, payload)])
        return pending.future.result(timeout=60)
