"""Replicated log abstraction.

Fills the role of the reference's vendored hashicorp/raft + BoltDB store
(nomad/server.go:1079 setupRaft). Two implementations:

- ``InProcRaft``: an in-process log for single-server (dev) mode and for
  multi-server tests — the leader appends entries and applies them to every
  peer FSM synchronously, giving the same linearizable apply order real raft
  provides (without network fault tolerance).
- A C++ consensus core is the planned native substrate for multi-host
  deployments (same ``apply`` contract); the control plane rides DCN, never
  ICI.
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Callable, List, Optional, Tuple

from .fsm import NomadFSM
from ..utils.lock_witness import witness_lock, witness_rlock


class NotLeaderError(Exception):
    pass


class InProcRaft:
    """Shared log; one elected leader; synchronous replication to peer FSMs.

    With ``data_dir`` set, every entry also lands in the C++ segmented log
    (nomad_tpu/native/log.py over native/nomadlog — the raft-boltdb slot),
    and a restarted process replays it back into the FSM on join. Snapshots
    (``snapshot()``) persist the FSM state and compact the log behind it,
    mirroring fsm.go:1059 Snapshot + log truncation.
    """

    def __init__(self, data_dir: Optional[str] = None, sync_writes: bool = False) -> None:
        self._lock = witness_rlock("raft.InProcRaft._lock")
        # serializes whole snapshot() operations with each other, never
        # with apply(): the durable write happens outside _lock
        self._snap_lock = witness_lock("raft.InProcRaft._snap_lock")
        self.log: List[Tuple[int, str, object]] = []
        self.last_index = 0
        self.fsms: List[NomadFSM] = []
        self.leader_idx: Optional[int] = None
        self.leadership_observers: List[Callable[[int, bool], None]] = []
        self.sync_writes = sync_writes
        self.store = None
        self._snapshot_path = None
        self._snapshot_state: Optional[bytes] = None
        self._snapshot_index = 0
        if data_dir is not None:
            from ..native.log import NativeLog

            os.makedirs(data_dir, exist_ok=True)
            self.store = NativeLog(os.path.join(data_dir, "log"))
            self._snapshot_path = os.path.join(data_dir, "snapshot.bin")
            self._restore_from_store()

    def _restore_from_store(self) -> None:
        """Load the newest snapshot, then replay the durable log tail."""
        snap_index = 0
        if self._snapshot_path and os.path.exists(self._snapshot_path):
            with open(self._snapshot_path, "rb") as f:
                snap_index, self._snapshot_state = pickle.load(f)
        else:
            self._snapshot_state = None
        first, last = self.store.first_index, self.store.last_index
        for index in range(max(first, snap_index + 1), last + 1):
            blob = self.store.get(index)
            if blob is None:
                continue
            entry_type, payload = pickle.loads(blob)
            self.log.append((index, entry_type, payload))
        self.last_index = max(last, snap_index)
        self._snapshot_index = snap_index

    def join(self, fsm: NomadFSM) -> int:
        """Add a server's FSM; returns its peer index. Restores the newest
        snapshot (if any) then replays the log."""
        with self._lock:
            if getattr(self, "_snapshot_state", None) is not None:
                fsm.restore(pickle.loads(self._snapshot_state))
            for index, entry_type, payload in self.log:
                fsm.apply(index, entry_type, payload)
            self.fsms.append(fsm)
            peer = len(self.fsms) - 1
            if self.leader_idx is None:
                self._elect(peer)
            return peer

    def snapshot(self, peer: int) -> int:
        """Persist the peer's FSM state; compact the durable log behind it
        (fsm.go:1059 Snapshot / SnapshotAfter).

        The (state, index) pair is captured atomically under ``_lock`` —
        a snapshot must never claim an index whose mutations it does not
        contain — but serialization and the fsync'd write happen OUTSIDE
        the lock, so concurrent ``apply`` traffic never stalls behind a
        large FSM dump. Installation re-checks under ``_lock`` that no
        newer snapshot landed meanwhile."""
        with self._snap_lock:
            with self._lock:
                if self.store is None or self._snapshot_path is None:
                    return 0
                state = self.fsms[peer].snapshot()
                index = self.last_index
                if index <= self._snapshot_index:
                    return self._snapshot_index
            # safe off-lock: StateStore.snapshot() is a point-in-time copy
            # whose rows are never mutated in place by later applies
            state_blob = pickle.dumps(state)
            blob = pickle.dumps((index, state_blob))
            tmp = self._snapshot_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            with self._lock:
                if self.store is None or index <= self._snapshot_index:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
                    return self._snapshot_index
                os.replace(tmp, self._snapshot_path)
                self.store.truncate_before(index + 1)
                self.store.sync()
                # compact the in-memory log too, and refresh the cached
                # snapshot state future join() calls restore from
                self._snapshot_state = state_blob
                self.log = [e for e in self.log if e[0] > index]
                self._snapshot_index = index
                return index

    def stats(self, peer: int = 0) -> dict:
        """WireRaft-shaped introspection (Operator.RaftStats)."""
        with self._lock:
            return {
                "state": "leader" if self.leader_idx == peer else "follower",
                "term": 0,
                "leader_id": self.leader_idx,
                "last_index": self.last_index,
                "commit_index": self.last_index,
                "applied_index": self.last_index,
                "num_peers": max(0, len(self.fsms) - 1),
                "snapshot_index": self._snapshot_index,
                "snapshots_installed": 0,
            }

    def close(self) -> None:
        with self._lock:
            store, self.store = self.store, None
        if store is not None:
            store.sync()
            store.close()

    def _elect(self, peer: int) -> None:
        old = self.leader_idx
        self.leader_idx = peer
        for observer in self.leadership_observers:
            observer(peer, True)
            if old is not None:
                observer(old, False)

    def transfer_leadership(self, peer: int) -> None:
        with self._lock:
            if peer >= len(self.fsms):
                raise ValueError(f"unknown peer {peer}")
            old = self.leader_idx
            self.leader_idx = peer
            for observer in self.leadership_observers:
                if old is not None:
                    observer(old, False)
                observer(peer, True)

    def is_leader(self, peer: int) -> bool:
        return self.leader_idx == peer

    def apply(self, peer: int, entry_type: str, payload) -> Tuple[int, object]:
        """Append + replicate + apply; returns (index, leader-FSM response)."""
        with self._lock:
            if self.leader_idx != peer:
                raise NotLeaderError(f"peer {peer} is not the leader")
            self.last_index += 1
            index = self.last_index
            self.log.append((index, entry_type, payload))
            if self.store is not None:
                self.store.append(
                    index, pickle.dumps((entry_type, payload)), sync=self.sync_writes
                )
            response = None
            for i, fsm in enumerate(self.fsms):
                r = fsm.apply(index, entry_type, payload)
                if i == peer:
                    response = r
            return index, response
