"""Replicated log abstraction.

Fills the role of the reference's vendored hashicorp/raft + BoltDB store
(nomad/server.go:1079 setupRaft). Two implementations:

- ``InProcRaft``: an in-process log for single-server (dev) mode and for
  multi-server tests — the leader appends entries and applies them to every
  peer FSM synchronously, giving the same linearizable apply order real raft
  provides (without network fault tolerance).
- A C++ consensus core is the planned native substrate for multi-host
  deployments (same ``apply`` contract); the control plane rides DCN, never
  ICI.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

from .fsm import NomadFSM


class NotLeaderError(Exception):
    pass


class InProcRaft:
    """Shared log; one elected leader; synchronous replication to peer FSMs."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.log: List[Tuple[int, str, object]] = []
        self.last_index = 0
        self.fsms: List[NomadFSM] = []
        self.leader_idx: Optional[int] = None
        self.leadership_observers: List[Callable[[int, bool], None]] = []

    def join(self, fsm: NomadFSM) -> int:
        """Add a server's FSM; returns its peer index. Replays the log."""
        with self._lock:
            for index, entry_type, payload in self.log:
                fsm.apply(index, entry_type, payload)
            self.fsms.append(fsm)
            peer = len(self.fsms) - 1
            if self.leader_idx is None:
                self._elect(peer)
            return peer

    def _elect(self, peer: int) -> None:
        old = self.leader_idx
        self.leader_idx = peer
        for observer in self.leadership_observers:
            observer(peer, True)
            if old is not None:
                observer(old, False)

    def transfer_leadership(self, peer: int) -> None:
        with self._lock:
            if peer >= len(self.fsms):
                raise ValueError(f"unknown peer {peer}")
            old = self.leader_idx
            self.leader_idx = peer
            for observer in self.leadership_observers:
                if old is not None:
                    observer(old, False)
                observer(peer, True)

    def is_leader(self, peer: int) -> bool:
        return self.leader_idx == peer

    def apply(self, peer: int, entry_type: str, payload) -> Tuple[int, object]:
        """Append + replicate + apply; returns (index, leader-FSM response)."""
        with self._lock:
            if self.leader_idx != peer:
                raise NotLeaderError(f"peer {peer} is not the leader")
            self.last_index += 1
            index = self.last_index
            self.log.append((index, entry_type, payload))
            response = None
            for i, fsm in enumerate(self.fsms):
                r = fsm.apply(index, entry_type, payload)
                if i == peer:
                    response = r
            return index, response
