"""The server: wires raft/FSM, broker, plan pipeline, workers, heartbeats.

Fills the role of reference ``nomad/server.go`` + ``nomad/leader.go``: on
gaining leadership the broker/blocked-tracker/plan-queue enable and pending
evals restore from state (leader.go:180 establishLeadership); on losing it
everything disables. Endpoint methods (register_*, update_*) are the
in-process equivalents of the RPC endpoint layer; a transport front-end
(msgpack/gRPC) binds to them at the process boundary.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs.structs import (
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_FAILED,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    JOB_TYPE_SERVICE,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    Allocation,
    Evaluation,
    Job,
    Node,
    SchedulerConfiguration,
    generate_uuid,
)
from ..chaos.injector import fire as chaos_fire
from .blocked_evals import BlockedEvals
from .eval_broker import EvalBroker
from .fsm import (
    ALLOC_CLIENT_UPDATE,
    EVAL_UPDATE,
    JOB_DEREGISTER,
    JOB_REGISTER,
    NODE_DEREGISTER,
    NODE_DRAIN_UPDATE,
    NODE_ELIGIBILITY_UPDATE,
    NODE_REGISTER,
    NODE_STATUS_UPDATE,
    SCHEDULER_CONFIG,
    NomadFSM,
)
from .heartbeat import HeartbeatTimers
from .plan_apply import Planner, PlanQueue
from .raft import InProcRaft
from .worker import Worker
from ..utils.lock_witness import witness_rlock


def leader_forward(rpc_method: str):
    """Follower-side write forwarding (reference nomad/rpc.go forward():
    every write endpoint relays to the leader before touching raft). A
    wire-raft FOLLOWER re-issues the call as the equivalent RPC — the
    transport routes it to the leader — so the method executes ENTIRELY
    on the leader and its read-after-write never races local replication.
    In-proc / leader / leaderless states run the local method unchanged
    (leaderless writes still fail with NotLeaderError, as the reference's
    forward() fails without a known leader)."""
    import functools
    import inspect

    def deco(fn):
        sig = inspect.signature(fn)

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            get_addr = getattr(self, "get_leader_rpc_addr", None)
            if get_addr is not None and not self.is_leader:
                addr = get_addr()
                if addr:
                    bound = sig.bind(self, *args, **kwargs)
                    bound.apply_defaults()
                    pos = list(bound.arguments.values())[1:]
                    return self.leader_conn.get(addr).call(rpc_method, *pos)
            return fn(self, *args, **kwargs)

        return wrapper

    return deco


@dataclass
class ServerConfig:
    num_schedulers: int = 2
    deterministic: bool = False
    # deterministic-mode per-eval candidate-ring seeding (the reference's
    # per-eval shuffle analog, util.go:329): decorrelates concurrent
    # evals so optimistic concurrency doesn't funnel every eval onto one
    # ring prefix. Harness/parity contexts leave it off.
    ring_decorrelate: bool = True
    # evals smaller than this skip the device dispatch and place on the
    # host iterator stack (reference-latency path for small jobs and
    # partial-commit retries); the device engine amortizes above it
    device_min_placements: int = 24
    heartbeat_min_ttl: float = 10.0
    heartbeat_max_ttl: float = 30.0
    eval_gc_interval: float = 300.0
    unblock_failed_interval: float = 60.0
    # -- capacity pressure (nomad_tpu/server/blocked_evals + autoscaler) --
    # unblock coalescing: capacity triggers landing within this window
    # merge into one batched, cross-trigger-deduped broker re-enqueue;
    # 0 flushes synchronously per trigger (the pre-storm behavior)
    unblock_coalesce_window_s: float = 0.05
    # per-flush cap on the re-enqueue batch — a 10K-eval unblock storm
    # reaches the broker as bounded batches, the remainder deferring one
    # window at a time
    unblock_max_batch: int = 512
    # leader autoscaler loop: reads blocked_evals.stats() every interval
    # and drives node registration/drain through harness-supplied
    # callbacks (Autoscaler.scale_up_fn / scale_down_fn; without them the
    # loop observes but never acts). interval <= 0 disables the tick.
    autoscaler_interval_s: float = 0.0
    autoscaler_cooldown_s: float = 3.0
    autoscaler_max_step: int = 8
    autoscaler_blocked_threshold: int = 1
    autoscaler_evals_per_node: int = 2
    autoscaler_drain_idle_ticks: int = 3
    # liveness watchdog (nomad-trace): when placement throughput is flat
    # for watchdog_stall_s while evals are in flight, dump broker stats,
    # per-worker current spans and thread stacks to the monitor stream.
    # watchdog_interval <= 0 disables the tick entirely.
    watchdog_interval: float = 10.0
    watchdog_stall_s: float = 30.0
    # flight recorder (nomad-flightrec): leader-owned background sampler
    # snapshotting gauges + direct probes every flight_interval_s into a
    # bounded ring of flight_retain frames, optionally spilling JSONL
    # under flight_spill_dir. <= 0 disables (strict no-op: no thread).
    flight_interval_s: float = 0.25
    flight_retain: int = 1024
    flight_spill_dir: str = ""
    scheduler_algorithm: str = "tpu_binpack"
    # chunked throughput tier (scheduler_algorithm = "tpu_binpack_chunked"):
    # top-K chunk size per scan step, and the fraction of chunk-placed
    # evals re-run through the bit-parity scan as a divergence spot-check
    chunk_k: int = 128
    parity_sample_rate: float = 0.05
    vault: Optional[object] = None  # integrations.vault.VaultConfig
    # Eval-batched device scheduling (SURVEY §2.6 row 1): up to this many
    # concurrently-scheduling evals share ONE device dispatch of the
    # batched placement scan. 0/1 disables batching (per-eval dispatch).
    device_batch: int = 8
    # how long the batcher waits for co-arriving evals before dispatching
    # (the total CAP when idle-gap or demand-aware gathering is on).
    # Sized as a pure BACKSTOP, not the gather pacing: with demand-aware
    # gathering (DeviceBatcher.expect) a wave dispatches the moment its
    # announced cohort has arrived — typically bounded by the concurrent
    # encode time, tens of ms — and this cap only bites when an announced
    # encode stalls. The old 25ms default silently amputated any cohort
    # whose encodes took longer than 25ms to trickle in, which at C1M
    # scale meant waves never filled (r05: mean 16 evals vs a 64 cap).
    device_batch_window_ms: float = 2000.0
    # adaptive gather: keep the batch growing while requests keep arriving
    # within this gap of each other (a burst's encodes trickle in);
    # 0 disables (fixed window only). ON by default: a lone eval pays at
    # most the idle gap (~3ms, well under one device dispatch), a burst
    # gathers into one dispatch, and window_ms caps the worst case —
    # the trickle-arrival latency bound is asserted by
    # tests/test_device_batcher.py::test_trickle_arrivals_latency.
    device_batch_idle_ms: float = 3.0
    # shard the eval batch over an ("evals", "nodes") jax device mesh when
    # multiple accelerator devices are visible (multi-chip)
    device_mesh: bool = False
    # -- asynchronous eval-lifecycle pipeline (nomad_tpu/pipeline) -----
    # master switch: leader-local workers hand device-built dense plans
    # to the async applier (commit + ack off the dispatch thread) so
    # eval waves overlap instead of convoying
    pipeline_async: bool = True
    # async waves in flight before workers fall back to the classic
    # synchronous submit (bounds applier memory and completion-queue
    # depth)
    pipeline_inflight: int = 128
    # device re-entries per wave on partial OCC commit (redispatch from
    # the wave's remembered encode) before nacking back to the broker
    pipeline_redispatch_max: int = 2
    # watchdog bound: an accepted wave unacked this long after its last
    # (re)enqueue is force-nacked — no eval strands in the pipeline
    pipeline_ack_timeout_s: float = 30.0
    # backoff between a wave's partial-commit redispatches (exponential
    # from this base, capped at the max): a flapping apply path degrades
    # to spaced retries instead of hot-looping device dispatches
    pipeline_redispatch_backoff_s: float = 0.05
    pipeline_redispatch_backoff_max_s: float = 1.0
    # bounded wait for a pipeline slot when inflight_max is saturated
    # (an unblock storm's re-enqueue spike): a transient spike defers
    # briefly and stays async, sustained saturation falls back to the
    # classic synchronous path (counted as nomad.pipeline.backpressure)
    pipeline_backpressure_wait_s: float = 0.02
    # -- watch hub / blocking queries (nomad_tpu/watch) ----------------
    # wakeup coalescing window: raft applies landing within it merge
    # into ONE flush, so an apply storm wakes each parked blocking query
    # once per window instead of once per write. 0 = synchronous wakeups
    # (per-apply, the reference's channel-close-per-write behavior)
    watch_coalesce_ms: float = 5.0
    # bound on parked watchers per replica; subscribe past it refuses
    # (WatchLimitError) and the read degrades to plain polling
    watch_max_watchers: int = 100_000
    # federation (reference leader.go:997/:1138): non-authoritative
    # regions' leaders mirror ACL policies and GLOBAL tokens from the
    # authoritative region. Empty authoritative_region (or equal to our
    # own region) disables replication.
    region: str = "global"
    authoritative_region: str = ""
    replication_token: str = ""
    replication_interval: float = 30.0


class Server:
    def __init__(
        self,
        config: Optional[ServerConfig] = None,
        raft: Optional[InProcRaft] = None,
        name: str = "server-1",
    ) -> None:
        self.config = config or ServerConfig()
        self.name = name
        self.logger = logging.getLogger(f"nomad_tpu.server.{name}")

        self.fsm = NomadFSM()
        # watch hub on EVERY replica (not leader-gated): followers notify
        # their local hub as entries replicate, which is what lets stale
        # reads park on a follower with min_query_index honored
        from ..watch.hub import WatchHub

        self.watch_hub = WatchHub(
            coalesce_ms=self.config.watch_coalesce_ms,
            max_watchers=self.config.watch_max_watchers,
        )
        self.fsm.watch_hub = self.watch_hub
        self.raft = raft or InProcRaft()
        self.eval_broker = EvalBroker()
        self.blocked_evals = BlockedEvals(
            self.eval_broker,
            coalesce_window_s=self.config.unblock_coalesce_window_s,
            max_batch=self.config.unblock_max_batch,
        )
        # leader autoscaler: armed with leadership below; inert until a
        # harness attaches scale_up_fn / scale_down_fn node providers
        from .autoscaler import Autoscaler

        self.autoscaler = Autoscaler(
            self.blocked_evals.stats,
            blocked_threshold=self.config.autoscaler_blocked_threshold,
            evals_per_node=self.config.autoscaler_evals_per_node,
            max_step=self.config.autoscaler_max_step,
            cooldown_s=self.config.autoscaler_cooldown_s,
            drain_idle_ticks=self.config.autoscaler_drain_idle_ticks,
        )
        self.plan_queue = PlanQueue()
        self.heartbeaters = HeartbeatTimers(
            self, self.config.heartbeat_min_ttl, self.config.heartbeat_max_ttl
        )
        self.workers: List[Worker] = []
        self.planner: Optional[Planner] = None
        self._leadership = False
        self._leader_generation = 0
        self._leader_timers: List[threading.Timer] = []
        self._lock = witness_rlock("server.Server._lock")

        # follower->leader write forwarding (leader_forward decorator):
        # one cached RPC client that follows the moving leader address.
        # Built lazily (property) so it picks up rpc_tls, which the agent
        # assigns after construction.
        self._leader_conn = None

        from .timetable import TimeTable

        self.timetable = TimeTable()
        # The FSM witnesses every applied index (including plan results and
        # entries replicated to followers), so GC cutoffs survive leader
        # transitions.
        self.fsm.timetable = self.timetable

        from .deploymentwatcher import DeploymentsWatcher
        from .drainer import NodeDrainer
        from .periodic import PeriodicDispatch

        self.deployment_watcher = DeploymentsWatcher(self)
        self.node_drainer = NodeDrainer(self)
        self.periodic_dispatcher = PeriodicDispatch(self)

        # Vault (nomad/vault.go): leader derives/revokes task tokens
        self.vault = None
        if self.config.vault is not None and getattr(self.config.vault, "enabled", False):
            from ..integrations.vault import VaultClient

            self.vault = VaultClient(self.config.vault)

        # Eval-batched device scheduling: workers submit encoded evals here
        # so K concurrent evals ride one device dispatch (the TPU-native
        # analog of the reference's N workers per server, server.go:1307).
        # The batcher's thread starts lazily on first use.
        self.device_batcher = None
        if self.config.device_batch > 1:
            from ..tpu.batcher import DeviceBatcher

            mesh = None
            if self.config.device_mesh:
                try:
                    import jax

                    from ..parallel import make_mesh

                    n_dev = len(jax.devices())
                    if n_dev > 1:
                        mesh = make_mesh(
                            n_dev,
                            eval_parallel=min(self.config.device_batch, n_dev),
                        )
                except Exception:  # noqa: BLE001 — no devices: run unsharded
                    mesh = None
            self.device_batcher = DeviceBatcher(
                max_batch=self.config.device_batch,
                window_ms=self.config.device_batch_window_ms,
                idle_ms=getattr(self.config, "device_batch_idle_ms", 0.0),
                mesh=mesh,
            )

        # Asynchronous eval-lifecycle pipeline (nomad_tpu/pipeline):
        # leader-only applier that owns commit + ack of device-built
        # dense plans; enabled/disabled with leadership below.
        self.pipeline = None
        if self.config.pipeline_async:
            from ..pipeline import AsyncApplier

            self.pipeline = AsyncApplier(
                self,
                inflight_max=self.config.pipeline_inflight,
                redispatch_max=self.config.pipeline_redispatch_max,
                ack_timeout_s=self.config.pipeline_ack_timeout_s,
                redispatch_backoff_s=self.config.pipeline_redispatch_backoff_s,
                redispatch_backoff_max_s=self.config.pipeline_redispatch_backoff_max_s,
                backpressure_wait_s=self.config.pipeline_backpressure_wait_s,
            )

        # Cross-region RPC hook (set by the agent): callable
        # (method, region, *args) routed through the gossip region map.
        self.region_rpc = None

        # first-job latency instrumentation (set once each)
        self._first_job_t0: Optional[float] = None
        self._first_job_latency_recorded = False

        # liveness watchdog: ticked from the leader timer loop (below);
        # the instance survives leadership churn, its progress baseline
        # re-seeds on the first tick of each generation
        from ..trace import FlightRecorder, LivenessWatchdog, \
            install_server_probes

        self.watchdog = LivenessWatchdog(
            self, stall_after=self.config.watchdog_stall_s
        )

        # flight recorder: armed with leadership (below), so followers
        # pay nothing; probes are wired once here — they all read through
        # self.* and survive leadership churn
        spill = None
        if self.config.flight_spill_dir:
            import os as _os

            _os.makedirs(self.config.flight_spill_dir, exist_ok=True)
            spill = _os.path.join(
                self.config.flight_spill_dir, f"{name}.flight.jsonl"
            )
        self.flight = FlightRecorder(
            interval_s=self.config.flight_interval_s,
            retain=self.config.flight_retain,
            spill_path=spill,
        )
        install_server_probes(self.flight, self)
        # the recorder tick drives the gauge publish so /v1/metrics stays
        # fresh even when the 10s stats sweep hasn't run yet (bench and
        # chaos harnesses poll gauges without an agent)
        self.flight.add_publisher(self.publish_stats_gauges)

        # Join before observing: the join-time election fires observers, and
        # start() handles the initial-leadership case explicitly.
        self.peer = self.raft.join(self.fsm)
        self.raft.leadership_observers.append(self._on_leadership)
        self.planner = Planner(self.raft, self.peer, self.fsm, self.plan_queue)

    # ------------------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.raft.is_leader(self.peer)

    @property
    def leader_conn(self):
        if self._leader_conn is None:
            from ..rpc.transport import LeaderConn

            self._leader_conn = LeaderConn(
                timeout=30.0, tls=getattr(self, "rpc_tls", None)
            )
        return self._leader_conn

    def raft_apply(self, entry_type: str, payload) -> Tuple[int, object]:
        # every log append funnels through here (plan commits take the
        # applier's own tracked region too — the phase union dedups): the
        # worker-thread applies (eval status updates, follow-up evals)
        # otherwise show up as unexplained worker_busy time
        from ..utils import phases

        chaos_fire("raft_apply", entry_type=entry_type)
        from ..trace import lifecycle as _lc

        t0 = _lc.pipeline_now()
        try:
            with phases.track("raft_fsm"):
                return self.raft.apply(self.peer, entry_type, payload)
        finally:
            # same span on the lifecycle (monotonic) clock, keyed by entry
            # type: attribution joins it against the wave windows (phases
            # uses perf_counter and bench-window unions — wrong clock and
            # wrong granularity for per-wave critical paths)
            _lc.pipeline_record("raft_fsm", entry_type, t0, _lc.pipeline_now())

    def start(self) -> None:
        for i in range(self.config.num_schedulers):
            w = Worker(self, i)
            self.workers.append(w)
            w.start()
        self.planner.start()
        if self.is_leader and not self._leadership:
            self._establish_leadership()

    def stop(self) -> None:
        for w in self.workers:
            w.stop()
        if self.planner is not None:
            self.planner.stop()
        if self.device_batcher is not None:
            self.device_batcher.stop()
        # wake every parked blocking query and stop the flusher thread
        self.watch_hub.close()
        self._revoke_leadership()

    # -- leadership ------------------------------------------------------

    def _on_leadership(self, peer: int, is_leader: bool) -> None:
        if peer != self.peer:
            return
        if is_leader:
            self._establish_leadership()
        else:
            self._revoke_leadership()

    def _establish_leadership(self) -> None:
        with self._lock:
            if self._leadership:
                return
            self._leadership = True
        self.logger.info("gained leadership")
        self.plan_queue.set_enabled(True)
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.heartbeaters.set_enabled(True)
        self.deployment_watcher.set_enabled(True)
        self.node_drainer.set_enabled(True)
        self.periodic_dispatcher.set_enabled(True)
        if self.pipeline is not None:
            self.pipeline.set_enabled(True)
        self.fsm.on_eval_upserted = self._handle_upserted_eval
        self.fsm.on_capacity_change = self.blocked_evals.unblock
        self._restore_evals()
        self._restore_heartbeats()
        if self.fsm.state.scheduler_config()[1] is None:
            self.raft_apply(
                SCHEDULER_CONFIG,
                SchedulerConfiguration(
                    scheduler_algorithm=self.config.scheduler_algorithm,
                    chunk_k=self.config.chunk_k,
                    parity_sample_rate=self.config.parity_sample_rate,
                ),
            )
        self._leader_generation += 1  # race-ok: leadership transitions run on the single raft notify thread
        gen = self._leader_generation
        self._schedule_leader_task(gen, self.config.unblock_failed_interval,
                                   self.blocked_evals.unblock_failed)
        self._schedule_leader_task(gen, self.config.unblock_failed_interval,
                                   self._reap_failed_evals)
        self._schedule_leader_task(gen, self.config.eval_gc_interval, self._create_gc_evals)
        self._schedule_leader_task(gen, 10.0, self.publish_stats_gauges)
        if self.config.watchdog_interval > 0:
            self._schedule_leader_task(
                gen, self.config.watchdog_interval, self.watchdog.tick
            )
        # autoscaler flies with leadership, like the watchdog/flight tasks
        if self.config.autoscaler_interval_s > 0:
            self.autoscaler.set_enabled(True)
            self._schedule_leader_task(
                gen, self.config.autoscaler_interval_s, self.autoscaler.tick
            )
        # flight recorder flies with leadership: followers run no sampler
        self.flight.arm()
        if self.vault is not None:
            self._schedule_leader_task(gen, 60.0, self._sweep_vault_accessors)
        if (self.config.authoritative_region
                and self.config.authoritative_region != self.config.region):
            # non-authoritative leader: mirror ACL state from the
            # authoritative region (leader.go:997 replicateACLPolicies,
            # :1138 replicateACLTokens)
            self._schedule_leader_task(
                gen, self.config.replication_interval, self._replicate_acl
            )

    def publish_stats_gauges(self) -> None:
        """Publish broker/blocked/plan-queue gauges (reference
        eval_broker.go:825 EmitStats, blocked_evals.go EmitStats,
        leader.go:603 job summary metrics). Driven from BOTH the 10s
        leader stats sweep and the flight recorder's tick, so gauges on
        /v1/metrics stay fresh on harnesses with no agent sweep."""
        from ..utils import metric_names, metrics

        bs = self.eval_broker.stats()
        metrics.set_gauge("nomad.broker.total_ready", bs.get("total_ready", 0))
        metrics.set_gauge("nomad.broker.total_unacked", bs.get("total_unacked", 0))
        metrics.set_gauge("nomad.broker.total_blocked", bs.get("total_blocked", 0))
        metrics.set_gauge(
            "nomad.broker.dequeue_waiters", bs.get("dequeue_waiters", 0)
        )
        blocked_stats = self.blocked_evals.stats()
        metric_names.publish_family("nomad.blocked_evals", blocked_stats)
        # storm ledger (unblock_to_place percentiles, batch sizes, peak
        # depth) rides the same sweep
        from ..trace import capacity as _capacity

        _capacity.note_blocked_depth(blocked_stats.get("total_blocked", 0))
        _capacity.publish_gauges()
        metric_names.publish_family("nomad.autoscaler", self.autoscaler.stats())
        if self.device_batcher is not None:
            metric_names.publish_family(
                "nomad.device_batcher", self.device_batcher.stats
            )
        metrics.set_gauge(
            "nomad.plan.queue_depth", self.plan_queue.stats().get("depth", 0)
        )
        if self.pipeline is not None:
            metric_names.publish_family("nomad.pipeline", self.pipeline.stats())
        metrics.set_gauge(
            "nomad.heartbeat.active", self.heartbeaters.num_active()
        )
        metrics.set_gauge("nomad.state.latest_index", self.fsm.state.latest_index)
        # eval-lifecycle tail latency (nomad.trace.eval_ms.p50/p95/p99,
        # slowest_inflight_ms, inflight) — same sweep, so /v1/metrics
        # carries the trace gauges without a /v1/trace round trip
        from ..trace import lifecycle as _trace_lc

        _trace_lc.publish_gauges()

    def _revoke_leadership(self) -> None:
        with self._lock:
            if not self._leadership:
                return
            self._leadership = False
        self.logger.info("lost leadership")
        self.fsm.on_eval_upserted = None
        self.fsm.on_capacity_change = None
        self.plan_queue.set_enabled(False)
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.heartbeaters.set_enabled(False)
        self.deployment_watcher.set_enabled(False)
        self.node_drainer.set_enabled(False)
        self.periodic_dispatcher.set_enabled(False)
        if self.pipeline is not None:
            self.pipeline.set_enabled(False)
        self.autoscaler.set_enabled(False)
        self.flight.disarm()
        self._leader_generation += 1  # invalidates in-flight leader timers  # race-ok: leadership transitions run on the single raft notify thread
        with self._lock:
            for t in self._leader_timers:
                t.cancel()
            self._leader_timers.clear()

    def _restore_evals(self) -> None:
        """Re-enqueue non-terminal evals on leadership (leader.go:295)."""
        for ev in self.fsm.state.evals():
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _restore_heartbeats(self) -> None:
        for node in self.fsm.state.nodes():
            if node.status != NODE_STATUS_DOWN:
                self.heartbeaters.reset_heartbeat_timer(node.id)

    def _schedule_leader_task(self, gen: int, interval: float, fn) -> None:
        """Run fn every interval while this leadership generation holds."""

        def tick():
            if self._leader_generation != gen or not self._leadership:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001
                self.logger.exception("leader task %s failed", fn.__name__)
            self._schedule_leader_task(gen, interval, fn)

        t = threading.Timer(interval, tick)
        t.daemon = True
        with self._lock:
            if self._leader_generation != gen:
                return
            self._leader_timers.append(t)
            # prune fired timers
            self._leader_timers = [x for x in self._leader_timers if x.is_alive() or x is t]
        t.start()

    def _reap_failed_evals(self) -> None:
        """Drain the _failed queue: mark failed + create follow-ups
        (reference leader.go:505)."""
        from .eval_broker import FAILED_QUEUE

        while True:
            evaluation, token = self.eval_broker.dequeue([FAILED_QUEUE], timeout=0.01)
            if evaluation is None:
                return
            updated = evaluation.copy()
            updated.status = EVAL_STATUS_FAILED
            updated.status_description = (
                f"evaluation reached delivery limit ({self.eval_broker.delivery_limit})"
            )
            follow_up = evaluation.create_failed_follow_up_eval(60 * 10**9)
            updated.next_eval = follow_up.id
            updated.update_modify_time()
            follow_up.update_modify_time()
            self.raft_apply(EVAL_UPDATE, [updated, follow_up])
            try:
                self.eval_broker.ack(evaluation.id, token)
            except Exception:  # noqa: BLE001
                pass

    def _create_gc_evals(self) -> None:
        """Enqueue internal _core GC evals (reference leader.go:441)."""
        from ..structs.structs import (
            CORE_JOB_DEPLOYMENT_GC,
            CORE_JOB_EVAL_GC,
            CORE_JOB_JOB_GC,
            CORE_JOB_NODE_GC,
            JOB_TYPE_CORE,
        )

        index = self.fsm.state.latest_index
        for core_job in (
            CORE_JOB_EVAL_GC,
            CORE_JOB_JOB_GC,
            CORE_JOB_NODE_GC,
            CORE_JOB_DEPLOYMENT_GC,
        ):
            ev = Evaluation(
                namespace="-",
                priority=200,
                type=JOB_TYPE_CORE,
                triggered_by="scheduled",
                job_id=core_job,
                status=EVAL_STATUS_PENDING,
                snapshot_index=index,
            )
            self.eval_broker.enqueue(ev)

    def _handle_upserted_eval(self, evaluation: Evaluation) -> None:
        """FSM hook: route fresh evals to broker/blocked (fsm.go:641)."""
        if evaluation.should_enqueue():
            self.eval_broker.enqueue(evaluation)
        elif evaluation.should_block():
            self.blocked_evals.block(evaluation)

    # ------------------------------------------------------------------
    # Endpoint surface (in-process RPC equivalents)
    # ------------------------------------------------------------------

    def register_node(self, node: Node) -> float:
        """Node.Register: upsert + heartbeat TTL."""
        self.raft_apply(NODE_REGISTER, node)
        return self.heartbeaters.reset_heartbeat_timer(node.id)

    @leader_forward("Node.Deregister")
    def deregister_node(self, node_id: str) -> None:
        self.heartbeaters.clear_heartbeat_timer(node_id)
        self.raft_apply(NODE_DEREGISTER, node_id)
        self.create_node_evals(node_id)

    def heartbeat(self, node_id: str) -> float:
        """Node.UpdateStatus(ready) via TTL reset."""
        node = self.fsm.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node {node_id} not registered")
        if node.status == NODE_STATUS_DOWN:
            self.raft_apply(NODE_STATUS_UPDATE, (node_id, NODE_STATUS_READY))
            self.create_node_evals(node_id)
        return self.heartbeaters.reset_heartbeat_timer(node_id)

    def update_node_status(self, node_id: str, status: str) -> None:
        self.raft_apply(NODE_STATUS_UPDATE, (node_id, status))
        self.create_node_evals(node_id)

    @leader_forward("Node.UpdateDrain")
    def update_node_drain(self, node_id: str, drain) -> None:
        """Node.UpdateDrain: ``drain`` is a DrainStrategy, True (default
        strategy), or falsy to cancel. The force deadline is stamped here —
        before the raft apply — so every replica agrees on it."""
        import copy as _copy

        from ..structs.structs import DrainStrategy

        if drain is True:
            drain = DrainStrategy()
        elif drain:
            drain = _copy.copy(drain)  # never mutate the caller's object
        if drain and drain.deadline_ns > 0 and drain.force_deadline_ns == 0:
            drain.force_deadline_ns = time.time_ns() + drain.deadline_ns
        self.raft_apply(NODE_DRAIN_UPDATE, (node_id, drain, not drain))
        if drain:
            self.create_node_evals(node_id)

    @leader_forward("Node.UpdateEligibility")
    def update_node_eligibility(self, node_id: str, eligibility: str) -> None:
        self.raft_apply(NODE_ELIGIBILITY_UPDATE, (node_id, eligibility))

    @leader_forward("Node.Evaluate")
    def create_node_evals(self, node_id: str) -> List[str]:
        """One eval per job with allocs on the node (node_endpoint.go)."""
        allocs = self.fsm.state.allocs_by_node(node_id)
        jobs = {}
        for alloc in allocs:
            jobs[(alloc.namespace, alloc.job_id)] = alloc
        evals = []
        for (namespace, job_id), alloc in jobs.items():
            job = self.fsm.state.job_by_id(namespace, job_id)
            ev = Evaluation(
                namespace=namespace,
                priority=job.priority if job else 50,
                type=job.type if job else JOB_TYPE_SERVICE,
                triggered_by=EVAL_TRIGGER_NODE_UPDATE,
                job_id=job_id,
                node_id=node_id,
                status=EVAL_STATUS_PENDING,
            )
            ev.update_modify_time()
            evals.append(ev)
        if evals:
            self.raft_apply(EVAL_UPDATE, evals)
        return [e.id for e in evals]

    # -- jobs ------------------------------------------------------------

    @leader_forward("Job.Register")
    def register_job(self, job: Job) -> str:
        """Job.Register: upsert + create an eval (job_endpoint.go:73)."""
        # first-job latency gauge (VERDICT r3 #3): time from the first
        # registration this process serves to its first plan commit
        if self._first_job_t0 is None:
            self._first_job_t0 = time.monotonic()  # race-ok: first-registration gauge; a lost duplicate set lands ~the same t0
        # Consul Connect admission mutator: group services with a connect
        # stanza get their sidecar task + proxy port injected BEFORE the
        # job hits raft (job_endpoint_hook_connect.go:99)
        from .job_hooks import job_connect_hook

        job_connect_hook(job)
        # Vault admission check (job_endpoint.go:175 validateJob): a job
        # asking for Vault tokens needs a Vault-enabled server
        if self.vault is None:
            for tg in job.task_groups:
                for task in tg.tasks:
                    if task.vault:
                        raise ValueError(
                            f"task {task.name!r} has a vault stanza but the "
                            "server has no Vault configured"
                        )
        self.raft_apply(JOB_REGISTER, job)
        stored = self.fsm.state.job_by_id(job.namespace, job.id)
        # track/update/untrack with the dispatcher on every registration so
        # disabling a job's periodic stanza stops its launches (periodic.go:Add)
        self.periodic_dispatcher.add(stored)
        if stored.is_periodic() or stored.is_parameterized():
            # periodic children spawn at launch times; parameterized templates
            # only run when dispatched (job_endpoint.go Register)
            return ""
        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            job_modify_index=stored.job_modify_index,
            status=EVAL_STATUS_PENDING,
        )
        ev.update_modify_time()
        self.raft_apply(EVAL_UPDATE, [ev])
        return ev.id

    @leader_forward("Job.Deregister")
    def deregister_job(self, namespace: str, job_id: str, purge: bool = False) -> str:
        job = self.fsm.state.job_by_id(namespace, job_id)
        self.raft_apply(JOB_DEREGISTER, (namespace, job_id, purge))
        self.blocked_evals.untrack(namespace, job_id)
        self.periodic_dispatcher.remove(namespace, job_id)
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by=EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
        )
        ev.update_modify_time()
        self.raft_apply(EVAL_UPDATE, [ev])
        return ev.id

    @leader_forward("Job.Evaluate")
    def evaluate_job(self, namespace: str, job_id: str) -> str:
        """Job.Evaluate: force a new evaluation (job_endpoint.go Evaluate)."""
        job = self.fsm.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id!r} not found")
        if job.is_periodic():
            raise ValueError("can't evaluate periodic job")
        if job.is_parameterized():
            raise ValueError("can't evaluate parameterized job")
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            job_modify_index=job.job_modify_index,
            status=EVAL_STATUS_PENDING,
        )
        ev.update_modify_time()
        self.raft_apply(EVAL_UPDATE, [ev])
        return ev.id

    @leader_forward("Job.Dispatch")
    def dispatch_job(
        self, namespace: str, job_id: str, payload: bytes = b"", meta=None
    ):
        """Job.Dispatch: instantiate a parameterized job (job_endpoint.go
        Dispatch). Returns (child_job_id, eval_id)."""
        parent = self.fsm.state.job_by_id(namespace, job_id)
        if parent is None:
            raise KeyError(f"job {job_id!r} not found")
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id!r} is not parameterized")
        if parent.stopped():
            raise ValueError(f"job {job_id!r} is stopped")
        cfg = parent.parameterized
        meta = dict(meta or {})
        if cfg.payload == "required" and not payload:
            raise ValueError("payload is required")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload is forbidden")
        for key in cfg.meta_required:
            if key not in meta:
                raise ValueError(f"missing required dispatch meta {key!r}")
        allowed = set(cfg.meta_required) | set(cfg.meta_optional)
        for key in meta:
            if key not in allowed:
                raise ValueError(f"dispatch meta {key!r} not allowed")

        child = parent.derive_child(
            "{}/dispatch-{}-{}".format(parent.id, int(time.time()), generate_uuid()[:8])
        )
        child.parameterized = None
        child.payload = bytes(payload)
        child.meta = {**parent.meta, **meta}
        eval_id = self.register_job(child)
        return child.id, eval_id

    @leader_forward("Job.Stability")
    def set_job_stability(
        self, namespace: str, job_id: str, version: int, stable: bool
    ) -> None:
        """Job.Stable (job_endpoint.go Stable)."""
        job = self.fsm.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id!r} not found")
        versions = self.fsm.state.job_versions.get((namespace, job_id), [])
        if not any(j.version == version for j in versions):
            raise ValueError(f"job {job_id!r} has no version {version}")
        self.raft_apply("job-stability", (namespace, job_id, version, stable))

    @leader_forward("Job.Revert")
    def revert_job(
        self,
        namespace: str,
        job_id: str,
        version: int,
        enforce_prior_version: Optional[int] = None,
    ) -> str:
        """Job.Revert: re-register a prior version (job_endpoint.go Revert)."""
        cur = self.fsm.state.job_by_id(namespace, job_id)
        if cur is None:
            raise KeyError(f"job {job_id!r} not found")
        if enforce_prior_version is not None and cur.version != enforce_prior_version:
            raise ValueError(
                f"current version is {cur.version}, not {enforce_prior_version}"
            )
        if version == cur.version:
            raise ValueError(f"can't revert to current version {version}")
        prior = self.fsm.state.job_by_id_and_version(namespace, job_id, version)
        if prior is None:
            raise KeyError(f"job {job_id!r} has no version {version}")
        revert = prior.copy()
        revert.stable = False
        revert.version = 0  # upsert assigns the next version
        return self.register_job(revert)

    def plan_job(self, job: Job, diff: bool = False):
        """Job.Plan: dry-run the scheduler against a snapshot with the
        submitted job inserted (job_endpoint.go Plan → scheduler harness);
        nothing raft-applies. Returns (annotations, failed_tg_allocs,
        job_modify_index, job_diff)."""
        from ..scheduler.scheduler import new_scheduler
        from ..scheduler.testing import Harness
        from ..structs.diff import job_diff

        snap = self.fsm.state.snapshot()
        index = snap.latest_index + 1
        old_job = snap.job_by_id(job.namespace, job.id)
        jdiff = job_diff(old_job, None if job.stop else job) if diff else None
        if job.stop:
            snap.delete_job(index, job.namespace, job.id)
        else:
            snap.upsert_job(index, job)
        harness = Harness(snap)
        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            job_modify_index=index,
            status=EVAL_STATUS_PENDING,
            annotate_plan=True,
        )
        sched = new_scheduler(job.type, self.logger, snap, harness)
        sched.process(ev)
        annotations = harness.plans[-1].annotations if harness.plans else None
        failed = {}
        for e in harness.evals + [ev]:
            if e.failed_tg_allocs:
                failed.update(e.failed_tg_allocs)
        return annotations, failed or None, index, jdiff

    @leader_forward("System.GC")
    def force_gc(self) -> None:
        """System.GarbageCollect: a forced core GC eval (system_endpoint.go)."""
        from .core_sched import CoreScheduler

        ev = Evaluation(
            namespace="-",
            priority=100,
            type="_core",
            triggered_by="force-gc",
            job_id="force-gc",
            status=EVAL_STATUS_PENDING,
        )
        CoreScheduler(self, self.fsm.state.snapshot()).process(ev)

    @leader_forward("Alloc.Stop")
    def stop_alloc(self, alloc_id: str) -> str:
        """Alloc.Stop: mark the alloc for migration and kick an eval
        (alloc_endpoint.go Stop)."""
        alloc = self.fsm.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id!r} not found")
        job = alloc.job or self.fsm.state.job_by_id(alloc.namespace, alloc.job_id)
        ev = Evaluation(
            namespace=alloc.namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by="alloc-stop",
            job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING,
        )
        ev.update_modify_time()
        from ..structs.structs import DesiredTransition

        self.raft_apply(
            "alloc-update-desired-transition",
            ({alloc_id: DesiredTransition(migrate=True)}, [ev]),
        )
        return ev.id

    # -- ACL (reference nomad/acl_endpoint.go) ---------------------------

    def bootstrap_acl(self):
        """One-shot creation of the initial management token
        (acl_endpoint.go Bootstrap)."""
        from ..structs.acl import bootstrap_token

        if self.fsm.state.acl_bootstrap_index != 0:
            raise ValueError("ACL bootstrap already done")
        token = bootstrap_token()
        self.raft_apply("acl-token-bootstrap", token)
        return self.fsm.state.acl_token_by_accessor(token.accessor_id)

    def upsert_acl_policies(self, policies) -> None:
        from ..acl import parse_policy

        for pol in policies:
            errors = pol.validate()
            if errors:
                raise ValueError("; ".join(errors))
            parse_policy(pol.rules)  # reject unparsable rules up front
        self.raft_apply("acl-policy-upsert", policies)

    def delete_acl_policies(self, names) -> None:
        self.raft_apply("acl-policy-delete", list(names))

    def upsert_acl_tokens(self, tokens):
        for tok in tokens:
            errors = tok.validate()
            if errors:
                raise ValueError("; ".join(errors))
        self.raft_apply("acl-token-upsert", tokens)
        return [self.fsm.state.acl_token_by_accessor(t.accessor_id) for t in tokens]

    def delete_acl_tokens(self, accessors) -> None:
        self.raft_apply("acl-token-delete", list(accessors))

    # -- cross-region ACL replication (leader.go:997/:1138) ---------------

    def list_acl_for_replication(self, secret: str = ""):
        """RPC: the authoritative region's full policy set + GLOBAL tokens
        for a replica region's mirror sweep. Token secrets cross the wire
        here, so the caller must present the replication token or a
        management token once ACLs are bootstrapped."""
        self._check_replication_auth(secret)
        state = self.fsm.state
        policies = list(state.acl_policies_table.values())
        tokens = [t for t in state.acl_tokens_table.values() if t.global_]
        return [policies, tokens]

    def _check_replication_auth(self, secret: str) -> None:
        state = self.fsm.state
        if not state.acl_tokens_table:
            return  # ACLs not bootstrapped: nothing secret to protect
        if self.config.replication_token and secret == self.config.replication_token:
            return
        tok = state.acl_token_by_secret(secret) if secret else None
        if tok is not None and tok.is_management():
            return
        raise PermissionError(
            "ACL replication requires the replication token or a management token"
        )

    def _replicate_acl(self) -> None:
        if self.region_rpc is None:
            return
        try:
            policies, tokens = self.region_rpc(
                "ACL.ListReplication",
                self.config.authoritative_region,
                self.config.replication_token,
            )
        except Exception as e:  # noqa: BLE001 — authoritative region away
            # misconfigured credentials never self-heal: surface them;
            # transient unreachability stays at debug
            if "PermissionError" in str(e):
                self.logger.warning(
                    "ACL replication rejected by %s: %s (check "
                    "replication_token)", self.config.authoritative_region, e,
                )
            else:
                self.logger.debug("ACL replication fetch failed: %s", e)
            return
        from .fsm import (
            ACL_POLICY_DELETE,
            ACL_POLICY_UPSERT,
            ACL_TOKEN_DELETE,
            ACL_TOKEN_UPSERT,
        )

        state = self.fsm.state
        # policies: content-compare (raft restamps indexes locally, so
        # index equality would re-upsert forever)
        remote_p = {p.name: p for p in policies}
        local_p = dict(state.acl_policies_table)
        deletes = [n for n in local_p if n not in remote_p]
        upserts = [
            p for n, p in remote_p.items()
            if n not in local_p
            or (local_p[n].rules, local_p[n].description)
            != (p.rules, p.description)
        ]
        if deletes:
            self.raft_apply(ACL_POLICY_DELETE, deletes)
        if upserts:
            self.raft_apply(ACL_POLICY_UPSERT, upserts)
        # tokens: only GLOBAL tokens mirror; local tokens stay local
        remote_t = {t.accessor_id: t for t in tokens}
        local_t = {
            a: t for a, t in state.acl_tokens_table.items() if t.global_
        }
        t_deletes = [a for a in local_t if a not in remote_t]

        def token_key(t):
            return (t.name, t.type, tuple(t.policies), t.secret_id)

        t_upserts = [
            t for a, t in remote_t.items()
            if a not in local_t or token_key(local_t[a]) != token_key(t)
        ]
        if t_deletes:
            self.raft_apply(ACL_TOKEN_DELETE, t_deletes)
        if t_upserts:
            self.raft_apply(ACL_TOKEN_UPSERT, t_upserts)

    # -- vault (nomad/vault.go + node_endpoint.go DeriveVaultToken) ------

    def derive_vault_token(
        self,
        alloc_id: str,
        task_names: List[str],
        node_id: str = "",
        node_secret: str = "",
    ) -> Dict[str, str]:
        """Create per-task Vault tokens for an alloc's tasks; accessors
        are raft-tracked so the tokens are revoked when the alloc dies.

        The caller must prove it is the node the alloc is placed on:
        (node_id, node_secret) must match the registered node's secret and
        the alloc must actually live there (node_endpoint.go:1370) —
        otherwise any RPC caller could mint tokens for any policy set."""
        if self.vault is None:
            raise ValueError("Vault is not configured on this server")
        node = self.fsm.state.node_by_id(node_id) if node_id else None
        if node is None or not node_secret or node.secret_id != node_secret:
            raise PermissionError("node secret mismatch")
        alloc = self.fsm.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id!r} not found")
        if alloc.node_id != node_id:
            raise PermissionError(
                f"alloc {alloc_id!r} is not placed on node {node_id!r}"
            )
        if alloc.terminal_status():
            raise ValueError(f"alloc {alloc_id!r} is terminal")
        job = alloc.job or self.fsm.state.job_by_id(alloc.namespace, alloc.job_id)
        tg = job.lookup_task_group(alloc.task_group) if job else None
        tasks = {t.name: t for t in (tg.tasks if tg else [])}
        tokens: Dict[str, str] = {}
        records = []
        for name in task_names:
            task = tasks.get(name)
            if task is None or not task.vault:
                raise ValueError(f"task {name!r} has no vault stanza")
            derived = self.vault.derive_token(list(task.vault.get("policies", [])))
            tokens[name] = derived["token"]
            records.append({
                "alloc_id": alloc_id, "task": name,
                "accessor": derived["accessor"],
            })
        from .fsm import VAULT_ACCESSOR_UPSERT

        self.raft_apply(VAULT_ACCESSOR_UPSERT, records)
        return tokens

    def _sweep_vault_accessors(self) -> None:
        """Leader retry sweep: revoke accessors whose allocs are terminal
        or gone but whose revocation previously failed (vault.go
        revokeDaemon semantics)."""
        if self.vault is None:
            return
        stale = []
        for alloc_id in list(self.fsm.state.vault_accessors_table):
            alloc = self.fsm.state.alloc_by_id(alloc_id)
            if alloc is None or alloc.terminal_status():
                stale.append(alloc_id)
        if stale:
            self._revoke_vault_accessors(stale)

    def _revoke_vault_accessors(self, alloc_ids: List[str]) -> None:
        """Revoke + untrack token accessors of dead allocs (vault.go
        RevokeTokens); failures stay tracked for the leader sweep."""
        if self.vault is None:
            return
        to_delete = []
        for alloc_id in alloc_ids:
            accessors = self.fsm.state.vault_accessors_by_alloc(alloc_id)
            if not accessors:
                continue
            failed = self.vault.revoke_accessors([a["accessor"] for a in accessors])
            if not failed:
                to_delete.append(alloc_id)
        if to_delete:
            from .fsm import VAULT_ACCESSOR_DELETE

            self.raft_apply(VAULT_ACCESSOR_DELETE, to_delete)

    # -- client sync -----------------------------------------------------

    def update_allocs_from_client(self, allocs: List[Allocation]) -> None:
        """Node.UpdateAlloc: client status sync; failed allocs trigger
        reschedule evals via their job (node_endpoint.go)."""
        self.raft_apply(ALLOC_CLIENT_UPDATE, allocs)
        dead = [a.id for a in allocs if a.terminal_status()]
        if dead and self.vault is not None:
            # off the RPC hot path: an unreachable Vault must not delay
            # reschedule evals; the leader sweep retries failures
            threading.Thread(
                target=self._revoke_vault_accessors, args=(dead,), daemon=True
            ).start()
        evals = []
        seen = set()
        for alloc in allocs:
            if alloc.client_status != "failed":
                continue
            stored = self.fsm.state.alloc_by_id(alloc.id)
            if stored is None or (stored.namespace, stored.job_id) in seen:
                continue
            seen.add((stored.namespace, stored.job_id))
            job = self.fsm.state.job_by_id(stored.namespace, stored.job_id)
            if job is None:
                continue
            ev = Evaluation(
                namespace=stored.namespace,
                priority=job.priority,
                type=job.type,
                triggered_by="alloc-failure",
                job_id=job.id,
                status=EVAL_STATUS_PENDING,
            )
            ev.update_modify_time()
            evals.append(ev)
        if evals:
            self.raft_apply(EVAL_UPDATE, evals)

    # -- introspection ---------------------------------------------------

    def drain_evals(self, timeout: float = 10.0) -> bool:
        """Wait until the broker has no ready/unacked work (test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            s = self.eval_broker.stats()
            if s["total_ready"] == 0 and s["total_unacked"] == 0 and s["total_waiting"] == 0:
                return True
            time.sleep(0.01)
        return False
