"""Raft-index <-> wall-clock witness table (reference ``nomad/timetable.go``).

GC thresholds are expressed in time but state is stamped with indexes; the
TimeTable records (index, time) witnesses so "older than 1h" translates to
"index below X".
"""
from __future__ import annotations

import threading
import time
from typing import List, Tuple
from ..utils.lock_witness import witness_lock

DEFAULT_MAX_ENTRIES = 512


class TimeTable:
    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES) -> None:
        self._lock = witness_lock("timetable.TimeTable._lock")
        self._entries: List[Tuple[int, int]] = []  # (index, time_ns) ascending
        self.max_entries = max_entries

    def witness(self, index: int, when_ns: int = 0) -> None:
        when_ns = when_ns or time.time_ns()
        with self._lock:
            if self._entries and index <= self._entries[-1][0]:
                return
            self._entries.append((index, when_ns))
            if len(self._entries) > self.max_entries:
                # keep every other old entry (coarsen history, keep range)
                self._entries = self._entries[::2] + self._entries[-1:]

    def nearest_index(self, when_ns: int) -> int:
        """Largest index witnessed at or before ``when_ns`` (0 if none)."""
        with self._lock:
            best = 0
            for index, t in self._entries:
                if t <= when_ns:
                    best = index
                else:
                    break
            return best
