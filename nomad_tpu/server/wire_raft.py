"""Raft consensus over the RPC transport — one node per server process.

Fills the role of the reference's vendored hashicorp/raft
(nomad/server.go:1079 setupRaft, nomad/raft_rpc.go RaftLayer): leader
election with randomized timeouts, term/vote persistence, log replication
with quorum commit, conflict rollback via next_index backtracking, and
snapshot install for followers whose needed entries were compacted. The
durable log rides the C++ segmented store (native/nomadlog — the
raft-boltdb slot); term/vote metadata sits beside it.

Interface-compatible with ``InProcRaft`` as the ``Server`` consumes it
(join / apply / is_leader / snapshot / leadership_observers / close), so a
server runs unchanged on either: in-proc for dev mode and tests, wire raft
for real multi-process clusters. ``apply`` blocks until the entry commits
on a quorum and is applied to the local FSM — the same linearizable
contract ``raftApply`` gives the reference (nomad/rpc.go raftApply).
"""
from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..rpc.codec import decode as codec_decode
from ..rpc.codec import encode as codec_encode
from ..rpc.transport import RPCClient, RPCError, RPCServer
from .fsm import NomadFSM
from .raft import NotLeaderError
from ..utils.lock_witness import witness_lock, witness_rlock

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


def _encode_fsm_state(state_store) -> bytes:
    """FSM snapshot → msgpack bytes through the typed struct codec.

    Snapshots cross the wire in InstallSnapshot, so they must never be
    pickled: arbitrary deserialization there would hand code execution to
    any peer that can reach the RPC port (the reference ships snapshot
    data as msgpack, nomad/fsm.go persist/restore)."""
    return codec_encode(state_store.__getstate__())


def _decode_fsm_state(blob: bytes):
    from ..state import StateStore

    store = StateStore.__new__(StateStore)
    store.__setstate__(codec_decode(blob))
    return store


def _decode_disk_blob(blob: bytes):
    """Decode a locally-persisted record (log entry / snapshot wrapper).

    New writes are always codec-encoded; data dirs written by builds that
    pickled local state still load (pickle is acceptable for LOCAL files
    we wrote ourselves — the wire never carries it)."""
    try:
        return codec_decode(blob)
    except Exception:  # noqa: BLE001 — legacy format
        import pickle  # local-disk fallback only

        return pickle.loads(blob)


@dataclass
class WireRaftConfig:
    node_id: str = "node-1"
    election_timeout_min: float = 0.5
    election_timeout_max: float = 1.0
    heartbeat_interval: float = 0.1
    rpc_timeout: float = 1.0
    apply_timeout: float = 10.0
    sync_writes: bool = False


class WireRaft:
    """A raft participant. ``peers`` maps node_id → RPC address of the
    other servers; the full cluster is peers + self (static bootstrap,
    the reference's ``bootstrap_expect`` pattern)."""

    def __init__(
        self,
        rpc: RPCServer,
        peers: Optional[Dict[str, Tuple[str, int]]] = None,
        config: Optional[WireRaftConfig] = None,
        data_dir: Optional[str] = None,
    ) -> None:
        self.config = config or WireRaftConfig()
        self.node_id = self.config.node_id
        self.logger = logging.getLogger(f"nomad_tpu.raft.{self.node_id}")
        self.rpc = rpc
        self.peers: Dict[str, Tuple[str, int]] = dict(peers or {})
        # staged (log-replicated) membership: peers added through the log
        # start as NONVOTERS — replicated to but outside quorum/election
        # math — and promote to voters once caught up (the reference gets
        # this from hashicorp/raft's staged configuration changes,
        # leader.go:859)
        self.nonvoters: set = set()
        self._self_nonvoter = False
        self._staged: Dict[str, int] = {}  # peer -> catch-up target index
        self._clients: Dict[str, RPCClient] = {}

        self._lock = witness_rlock("wire_raft.WireRaft._lock")
        self._snap_lock = witness_lock("wire_raft.WireRaft._snap_lock")
        self._commit_cv = threading.Condition(self._lock)
        self._repl_cv = threading.Condition(self._lock)
        self._snapshots_installed = 0

        # persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        # log entries as (index, term, entry_type, payload); index-contiguous,
        # starting after the snapshot boundary
        self.log: List[Tuple[int, int, str, object]] = []
        self._snapshot_index = 0
        self._snapshot_term = 0
        self._snapshot_state: Optional[bytes] = None
        self._snapshot_config: Optional[dict] = None

        # volatile state
        self.state = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._apply_results: Dict[int, object] = {}

        self.fsm: Optional[NomadFSM] = None
        self.leadership_observers: List[Callable[[int, bool], None]] = []
        self._was_leader = False

        self.store = None
        self._meta_path = None
        self._snapshot_path = None
        if data_dir is not None:
            from ..native.log import NativeLog

            os.makedirs(data_dir, exist_ok=True)
            self.store = NativeLog(os.path.join(data_dir, "log"))
            self._meta_path = os.path.join(data_dir, "raft_meta.json")
            self._snapshot_path = os.path.join(data_dir, "snapshot.bin")
            self._load_persistent()

        self._shutdown = threading.Event()
        self._started = False
        self._config_replay_boundary = 0
        self._last_contact = time.monotonic()
        self._election_deadline = self._random_deadline()
        self._threads: List[threading.Thread] = []

        rpc.register("Raft.RequestVote", self._handle_request_vote)
        rpc.register("Raft.AppendEntries", self._handle_append_entries)
        rpc.register("Raft.InstallSnapshot", self._handle_install_snapshot)

    # -- InProcRaft-compatible surface -----------------------------------

    def join(self, fsm: NomadFSM) -> int:
        """Attach the local FSM (exactly one per process); restores the
        snapshot + replays committed log. Returns peer handle 0."""
        with self._lock:
            if self.fsm is not None:
                raise ValueError("wire raft hosts exactly one FSM")
            self.fsm = fsm
            if self._snapshot_state is not None:
                fsm.restore(_decode_fsm_state(self._snapshot_state))
                self.last_applied = self._snapshot_index
            # committed entries re-apply on restart via commit advancement;
            # a lone node (no peers) self-commits everything it has
            if not self.peers:
                self.commit_index = self._last_index()
                self._apply_committed_locked()
        return 0

    def is_leader(self, peer: int = 0) -> bool:
        return self.state == LEADER

    def apply(self, peer: int, entry_type: str, payload) -> Tuple[int, object]:
        """Leader-only: append, replicate to quorum, apply, return
        (index, local FSM response)."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(
                    f"{self.node_id} is not the leader (leader={self.leader_id})"
                )
            index = self._last_index() + 1
            term = self.current_term
            self._append_locked(index, term, entry_type, payload)
            self.match_index[self.node_id] = index
            self._repl_cv.notify_all()
            if not self.peers:
                self._advance_commit_locked()
            deadline = time.monotonic() + self.config.apply_timeout
            while self.commit_index < index or self.last_applied < index:
                if self.state != LEADER or self.current_term != term:
                    raise NotLeaderError("lost leadership during apply")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"apply of index {index} timed out")
                self._commit_cv.wait(remaining)
            return index, self._apply_results.pop(index, None)

    def snapshot(self, peer: int = 0) -> int:
        """Snapshot the FSM and compact the log (fsm.go:1059).

        Capture (state, applied index, term, membership) is atomic under
        ``_lock``; the codec encode and the fsync'd file write run OUTSIDE
        it, so a large FSM dump never stalls appends, commit advancement
        or the replicator heartbeats (a leader serializing a big snapshot
        under the lock reads as a dead leader to its peers). Installation
        re-checks under ``_lock`` that no newer snapshot — e.g. a
        concurrent InstallSnapshot — landed meanwhile."""
        with self._snap_lock:
            with self._lock:
                if self.fsm is None:
                    return 0
                index = self.last_applied
                if index == 0:
                    return 0
                if index <= self._snapshot_index:
                    return self._snapshot_index
                term = self._term_at(index)
                state = self.fsm.snapshot()
                # membership rides the snapshot (hashicorp/raft stores the
                # configuration in snapshot meta): a follower caught up via
                # InstallSnapshot must learn peers whose PEER_ADD entries
                # were compacted away
                config = self._config_snapshot_locked()
            # safe off-lock: fsm.snapshot() is a point-in-time store copy
            # whose rows later applies never mutate in place
            state_blob = _encode_fsm_state(state)
            tmp = None
            if self._snapshot_path is not None:
                tmp = self._snapshot_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(codec_encode((index, term, state_blob, config)))
                    f.flush()
                    os.fsync(f.fileno())
            with self._lock:
                if index <= self._snapshot_index:
                    if tmp is not None:
                        try:
                            os.remove(tmp)
                        except OSError:
                            pass
                    return self._snapshot_index
                self._snapshot_state = state_blob
                self._snapshot_term = term
                self._snapshot_config = config
                self.log = [e for e in self.log if e[0] > index]
                self._snapshot_index = index
                if tmp is not None:
                    os.replace(tmp, self._snapshot_path)
                if self.store is not None:
                    self.store.truncate_before(index + 1)
                    self.store.sync()
                return index

    def close(self) -> None:
        self._shutdown.set()
        with self._lock:
            self._repl_cv.notify_all()
            self._commit_cv.notify_all()
        for c in self._clients.values():
            c.close()
        # atomic handoff: appenders hold _lock around their
        # `store is not None` check, so they see the store or None,
        # never a closed handle
        with self._lock:
            store, self.store = self.store, None
        if store is not None:
            store.sync()
            store.close()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "WireRaft":
        self._started = True  # race-ok: start() is called once, before any raft thread exists
        # membership-change entries at or below this index are HISTORY:
        # replaying them during catch-up would remove peers that have since
        # rejoined (the live peer set comes from gossip bootstrap). Only
        # entries committed after we started participating apply.
        with self._lock:
            self._config_replay_boundary = self._last_index()
        t = threading.Thread(
            target=self._election_loop, name=f"raft-election-{self.node_id}", daemon=True
        )
        t.start()
        self._threads.append(t)  # race-ok: GIL-atomic append; only read at shutdown
        for peer_id in list(self.peers):
            self._start_replicator(peer_id)
        if not self.peers:
            # single-node cluster: immediate self-election
            with self._lock:
                self._become_leader_locked(self.current_term + 1)
        return self

    def _start_replicator(self, peer_id: str) -> None:
        t = threading.Thread(
            target=self._replicator, args=(peer_id,),
            name=f"raft-repl-{self.node_id}-{peer_id}", daemon=True,
        )
        t.start()
        self._threads.append(t)  # race-ok: GIL-atomic append; only read at shutdown

    def add_peer(self, peer_id: str, addr: Tuple[str, int]) -> None:
        """Gossip-driven peer reconciliation (reference leader.go:859
        addRaftPeer — serf member join → raft configuration). A known peer
        gossiping a NEW address (restart with an ephemeral port) gets its
        connection retargeted."""
        addr = tuple(addr)
        stale_client = None
        with self._lock:
            if peer_id == self.node_id:
                return
            existing = self.peers.get(peer_id)
            if existing == addr:
                return
            self.peers[peer_id] = addr
            if existing is not None:
                # address change: drop the stale connection; the live
                # replicator thread picks up the new address next round
                stale_client = self._clients.pop(peer_id, None)
                new_peer = False
            else:
                new_peer = True
            if self.state == LEADER:
                self.next_index[peer_id] = self._last_index() + 1
                self.match_index.setdefault(peer_id, 0)
            started = self._started
        if stale_client is not None:
            stale_client.close()
        if started and new_peer:
            self._start_replicator(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        """leader.go:952 removeRaftPeer — LOCAL view only. For a
        cluster-wide removal (autopilot dead-server cleanup) use
        remove_peer_replicated, or every node's quorum math diverges."""
        with self._lock:
            self.peers.pop(peer_id, None)
            self.next_index.pop(peer_id, None)
            self.match_index.pop(peer_id, None)
            if peer_id in self.nonvoters:
                self.nonvoters.discard(peer_id)
                self._persist_meta_locked()
            self._staged.pop(peer_id, None)
            client = self._clients.pop(peer_id, None)
        if client is not None:
            client.close()

    PEER_REMOVE = "_raft-peer-remove"
    PEER_ADD = "_raft-peer-add"

    def remove_peer_replicated(self, peer_id: str) -> None:
        """Leader-only: commit the removal through the log so every
        replica shrinks its configuration at the same log position (the
        single-server membership-change protocol)."""
        self.apply(0, self.PEER_REMOVE, peer_id)

    def note_peer_address(self, peer_id: str, addr: Tuple[str, int]) -> None:
        """Gossip address retarget for an ALREADY-CONFIGURED peer (restart
        with an ephemeral port). Never grows the configuration — adds go
        through the log (add_peer_staged)."""
        with self._lock:
            if peer_id not in self.peers:
                return
        self.add_peer(peer_id, addr)

    def add_peer_staged(self, peer_id: str, addr: Tuple[str, int]) -> bool:
        """Leader-only log-replicated peer addition: the peer enters the
        configuration as a NONVOTER (replicated to, excluded from quorum
        and elections) and is promoted to voter once its match index
        reaches the staging point — so a minority partition can never
        grow its own voter set, and an add during a partition commits on
        exactly one side. Returns False when not leader (the caller
        retries after the next leadership change)."""
        addr = tuple(addr)
        with self._lock:
            if peer_id == self.node_id:
                return True
            if self.state != LEADER:
                return False
            if peer_id in self.peers:
                # known peer (voter OR in-flight nonvoter): retarget its
                # address if gossip reports a new one — a staged peer that
                # restarted on a fresh port must still be reachable or it
                # can never catch up and promote
                retarget = self.peers.get(peer_id) != addr
                stage = False
            else:
                retarget = False
                stage = peer_id not in self._staged
        if retarget:
            self.add_peer(peer_id, addr)
        if stage:
            self._apply_async(
                self.PEER_ADD, {"id": peer_id, "addr": list(addr), "voter": False}
            )
        return True

    def _apply_async(self, entry_type: str, payload) -> None:
        """Leader-side append WITHOUT waiting for commit (safe from
        replicator threads, which must not block on their own quorum)."""
        with self._lock:
            if self.state != LEADER:
                return
            index = self._last_index() + 1
            self._append_locked(index, self.current_term, entry_type, payload)
            self.match_index[self.node_id] = index
            self._repl_cv.notify_all()
            if not self._voter_peers():
                self._advance_commit_locked()

    def _voter_peers(self):
        return [p for p in self.peers if p not in self.nonvoters]

    def _config_snapshot_locked(self) -> dict:
        return {
            "peers": {pid: list(addr) for pid, addr in self.peers.items()},
            "nonvoters": sorted(self.nonvoters),
        }

    def _apply_snapshot_config_locked(self, config, voter_overlay: bool = True) -> None:
        """Adopt the membership carried by an installed snapshot.

        ``voter_overlay=False`` adopts only the PEER SET (addresses):
        used on local restart, where the persisted meta's voter/nonvoter
        overlay is at least as new as the snapshot's (it is rewritten on
        every membership change) and must not be reverted to the
        snapshot-time view."""
        if not config:
            return
        for pid, addr in (config.get("peers") or {}).items():
            if pid != self.node_id:
                self.add_peer(pid, tuple(addr))
        if voter_overlay:
            nv = set(config.get("nonvoters") or [])
            self._self_nonvoter = self.node_id in nv
            self.nonvoters = {p for p in nv if p != self.node_id}
            self._persist_meta_locked()

    # -- persistence -----------------------------------------------------

    def _load_persistent(self) -> None:
        if self._meta_path and os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                meta = json.load(f)
            self.current_term = meta.get("term", 0)
            self.voted_for = meta.get("voted_for")
            # voter/nonvoter overlay survives restarts (the replicated
            # config entries are replay-skipped behind the boundary, so
            # without this a restarted node would forget who is staged
            # — and a restarted nonvoter would campaign)
            self.nonvoters = set(meta.get("nonvoters", []))
            self._self_nonvoter = bool(meta.get("self_nonvoter", False))
        if self._snapshot_path and os.path.exists(self._snapshot_path):
            with open(self._snapshot_path, "rb") as f:
                record = _decode_disk_blob(f.read())
            if len(record) == 4:
                index, term, state_blob, snap_config = record
            else:  # pre-membership-snapshot format
                index, term, state_blob = record
                snap_config = None
            try:
                codec_decode(state_blob)
            except Exception:  # noqa: BLE001 — legacy pickled StateStore:
                # normalize now so restore and InstallSnapshot only ever
                # see codec bytes
                import pickle

                state_blob = _encode_fsm_state(pickle.loads(state_blob))
            self._snapshot_index = index
            self._snapshot_term = term
            self._snapshot_state = state_blob
            self._snapshot_config = snap_config
            if snap_config:
                # peers only: the meta overlay loaded above is newer than
                # the snapshot-time voter/nonvoter view
                self._apply_snapshot_config_locked(snap_config,
                                                   voter_overlay=False)
        if self.store is not None:
            first, last = self.store.first_index, self.store.last_index
            for index in range(max(first, self._snapshot_index + 1), last + 1):
                blob = self.store.get(index)
                if blob is None:
                    continue
                term, entry_type, payload = _decode_disk_blob(blob)
                self.log.append((index, term, entry_type, payload))

    def _persist_meta_locked(self) -> None:
        if self._meta_path is None:
            return
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "term": self.current_term, "voted_for": self.voted_for,
                "nonvoters": sorted(self.nonvoters),
                "self_nonvoter": self._self_nonvoter,
            }, f)
        os.replace(tmp, self._meta_path)

    def _append_locked(self, index: int, term: int, entry_type: str, payload) -> None:
        self.log.append((index, term, entry_type, payload))
        if self.store is not None:
            self.store.append(
                index,
                codec_encode((term, entry_type, payload)),
                sync=self.config.sync_writes,
            )

    # -- log helpers (hold lock) -----------------------------------------

    def _last_index(self) -> int:
        return self.log[-1][0] if self.log else self._snapshot_index

    def _last_term(self) -> int:
        return self.log[-1][1] if self.log else self._snapshot_term

    def _term_at(self, index: int) -> int:
        if index == self._snapshot_index:
            return self._snapshot_term
        if index == 0:
            return 0
        pos = index - self._snapshot_index - 1
        if 0 <= pos < len(self.log):
            return self.log[pos][1]
        return -1  # unknown (compacted or beyond tail)

    def _entries_from(self, index: int, limit: int = 512):
        pos = index - self._snapshot_index - 1
        if pos < 0:
            return None  # compacted — needs snapshot
        return self.log[pos:pos + limit]

    # -- roles -----------------------------------------------------------

    def _random_deadline(self) -> float:
        return time.monotonic() + random.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _notify_leadership(self, gained: bool) -> None:
        for observer in list(self.leadership_observers):
            try:
                observer(0, gained)
            except Exception:  # noqa: BLE001
                self.logger.exception("leadership observer failed")

    def _step_down_locked(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta_locked()
        self._election_deadline = self._random_deadline()
        if was_leader:
            self._was_leader = False
            self._commit_cv.notify_all()
            threading.Thread(
                target=self._notify_leadership, args=(False,), daemon=True
            ).start()

    def _become_leader_locked(self, term: int) -> None:
        self.state = LEADER
        self.current_term = term
        self.leader_id = self.node_id
        last = self._last_index()
        for peer_id in self.peers:
            self.next_index[peer_id] = last + 1
            self.match_index[peer_id] = 0
        self.match_index[self.node_id] = last
        # staging bookkeeping is leader-local: a new leader re-stages any
        # nonvoters it inherited from the replicated config so their
        # promotion still happens
        for peer_id in self.nonvoters:
            self._staged[peer_id] = last
        self._persist_meta_locked()
        self._was_leader = True
        # a no-op barrier entry lets the new leader commit entries from
        # prior terms (raft §5.4.2 — only current-term entries count
        # toward commit)
        self._append_locked(last + 1, term, "_raft-barrier", None)
        self.match_index[self.node_id] = last + 1
        self._repl_cv.notify_all()
        if not self.peers:
            self._advance_commit_locked()
        threading.Thread(target=self._notify_leadership, args=(True,), daemon=True).start()

    # -- election --------------------------------------------------------

    def _election_loop(self) -> None:
        while not self._shutdown.wait(0.02):
            with self._lock:
                if self.state == LEADER:
                    continue
                if time.monotonic() < self._election_deadline:
                    continue
                if self._self_nonvoter:
                    # staged nonvoters never campaign; the leader promotes
                    # them once caught up
                    self._election_deadline = self._random_deadline()
                    continue
                # start an election
                self.state = CANDIDATE
                self.current_term += 1
                term = self.current_term
                self.voted_for = self.node_id
                self._persist_meta_locked()
                self._election_deadline = self._random_deadline()
                last_index = self._last_index()
                last_term = self._last_term()
                voters = self._voter_peers()
            votes = 1
            needed = (len(voters) + 1) // 2 + 1
            for peer_id in list(voters):
                if self._shutdown.is_set():
                    return
                try:
                    r_term, granted = self._client(peer_id).call(
                        "Raft.RequestVote", term, self.node_id, last_index, last_term,
                        no_forward=True,
                    )
                except (RPCError, OSError, ConnectionError):
                    continue
                with self._lock:
                    if r_term > self.current_term:
                        self._step_down_locked(r_term)
                        break
                if granted:
                    votes += 1
            with self._lock:
                if self.state == CANDIDATE and self.current_term == term and votes >= needed:
                    self._become_leader_locked(term)

    def _handle_request_vote(self, term, candidate_id, last_log_index, last_log_term):
        with self._lock:
            if term < self.current_term:
                return [self.current_term, False]
            if term > self.current_term:
                self._step_down_locked(term)
            up_to_date = (last_log_term, last_log_index) >= (
                self._last_term(), self._last_index()
            )
            if up_to_date and self.voted_for in (None, candidate_id):
                self.voted_for = candidate_id
                self._persist_meta_locked()
                self._election_deadline = self._random_deadline()
                return [self.current_term, True]
            return [self.current_term, False]

    # -- replication (leader side) ---------------------------------------

    def _client(self, peer_id: str) -> RPCClient:
        c = self._clients.get(peer_id)
        if c is None:
            host, port = self.peers[peer_id]
            c = self._clients[peer_id] = RPCClient(  # race-ok: idempotent cache fill; worst case a duplicate client is dropped
                host, port, timeout=self.config.rpc_timeout,
                tls=getattr(self.rpc, "tls", None),
            )
        return c

    def _replicator(self, peer_id: str) -> None:
        """Per-peer loop: push entries whenever we lead and the peer lags;
        otherwise heartbeat on the interval."""
        while not self._shutdown.is_set():
            with self._lock:
                self._repl_cv.wait(self.config.heartbeat_interval)
                if peer_id not in self.peers:
                    return  # removed via remove_peer
                if self.state != LEADER:
                    continue
                term = self.current_term
            try:
                self._replicate_once(peer_id, term)
            except (RPCError, OSError, ConnectionError):
                continue
            except Exception:  # noqa: BLE001
                self.logger.exception("replication to %s failed", peer_id)

    def _replicate_once(self, peer_id: str, term: int) -> None:
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            next_idx = self.next_index.get(peer_id, self._last_index() + 1)
            prev_index = next_idx - 1
            prev_term = self._term_at(prev_index)
            entries = self._entries_from(next_idx)
            commit = self.commit_index
            if entries is None or prev_term < 0:
                # peer needs entries we compacted — install snapshot
                snap_index = self._snapshot_index
                snap_term = self._snapshot_term
                snap_state = self._snapshot_state
                snap_config = self._snapshot_config
                send_snapshot = True
            else:
                send_snapshot = False
                wire_entries = [list(e) for e in entries]
        if send_snapshot:
            if snap_state is None:
                return
            r_term = self._client(peer_id).call(
                "Raft.InstallSnapshot", term, self.node_id,
                snap_index, snap_term, snap_state, snap_config,
                no_forward=True,
            )
            with self._lock:
                if r_term > self.current_term:
                    self._step_down_locked(r_term)
                    return
                self.next_index[peer_id] = snap_index + 1
                self.match_index[peer_id] = max(
                    self.match_index.get(peer_id, 0), snap_index
                )
                self._advance_commit_locked()
            return
        r_term, success, match = self._client(peer_id).call(
            "Raft.AppendEntries", term, self.node_id,
            prev_index, prev_term, wire_entries, commit, no_forward=True,
        )
        with self._lock:
            if r_term > self.current_term:
                self._step_down_locked(r_term)
                return
            if self.state != LEADER or self.current_term != term:
                return
            if success:
                self.match_index[peer_id] = max(
                    self.match_index.get(peer_id, 0), match
                )
                self.next_index[peer_id] = self.match_index[peer_id] + 1
                self._advance_commit_locked()
                # staged nonvoter caught up -> promote to voter through
                # the log (async append; RLock makes this re-entrant)
                target = self._staged.get(peer_id)
                if target is not None and self.match_index[peer_id] >= target:
                    self._staged.pop(peer_id, None)
                    addr = self.peers.get(peer_id)
                    if addr is not None:
                        self._apply_async(self.PEER_ADD, {
                            "id": peer_id, "addr": list(addr), "voter": True,
                        })
                if self.next_index[peer_id] <= self._last_index():
                    self._repl_cv.notify_all()  # more to send
            else:
                # consistency check failed: the hint is the peer's last
                # index (back up past gaps) or its snapshot boundary (jump
                # FORWARD — everything at or below it is committed there)
                if match + 1 > next_idx:
                    self.next_index[peer_id] = match + 1
                else:
                    self.next_index[peer_id] = max(1, min(next_idx - 1, match + 1))
                self._repl_cv.notify_all()

    def _advance_commit_locked(self) -> None:
        """Commit = highest index replicated on a VOTER quorum, current
        term only (nonvoters receive entries but never count)."""
        voters = self._voter_peers()
        cluster = len(voters) + 1
        needed = cluster // 2 + 1
        voter_set = set(voters) | {self.node_id}
        for index in range(self._last_index(), self.commit_index, -1):
            if self._term_at(index) != self.current_term:
                break
            count = sum(
                1 for p, m in self.match_index.items()
                if m >= index and p in voter_set
            )
            if count >= needed:
                self.commit_index = index
                break
        self._apply_committed_locked()

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._entries_from(self.last_applied, 1)
            if not entry:
                # entry not present (should be unreachable): roll back the
                # counter so the index is retried rather than silently
                # skipped — skipping would diverge the FSM from the log
                self.last_applied -= 1
                break
            index, term, entry_type, payload = entry[0]
            if entry_type == self.PEER_REMOVE:
                boundary = getattr(self, "_config_replay_boundary", 0)
                if payload != self.node_id and index > boundary:
                    # RLock: safe to re-enter remove_peer while applying
                    self.remove_peer(payload)
                if self.state == LEADER:
                    self._apply_results[index] = None
                continue
            if entry_type == self.PEER_ADD:
                boundary = getattr(self, "_config_replay_boundary", 0)
                # entries about SELF always apply (in order, latest wins):
                # a fresh joiner's own PEER_ADD sits at/below its replay
                # boundary, and skipping it would leave the staged
                # nonvoter thinking it may campaign
                if index > boundary or payload.get("id") == self.node_id:
                    pid = payload["id"]
                    voter = bool(payload.get("voter"))
                    if pid == self.node_id:
                        # we're the subject: learn our own voter status
                        self._self_nonvoter = not voter
                    else:
                        self.add_peer(pid, tuple(payload.get("addr") or ()))
                        if voter:
                            self.nonvoters.discard(pid)
                            self._staged.pop(pid, None)
                        else:
                            self.nonvoters.add(pid)
                            if self.state == LEADER:
                                # promote once the peer catches up to HERE
                                self._staged[pid] = index
                    self._persist_meta_locked()
                if self.state == LEADER:
                    self._apply_results[index] = None
                continue
            if entry_type != "_raft-barrier" and self.fsm is not None:
                try:
                    result = self.fsm.apply(index, entry_type, payload)
                except Exception as e:  # noqa: BLE001
                    self.logger.exception("FSM apply failed at %d", index)
                    result = e
                if self.state == LEADER:
                    self._apply_results[index] = result
        self._commit_cv.notify_all()

    # -- follower side ---------------------------------------------------

    def _handle_append_entries(
        self, term, leader_id, prev_index, prev_term, entries, leader_commit
    ):
        with self._lock:
            if term < self.current_term:
                return [self.current_term, False, self._last_index()]
            if term > self.current_term or self.state != FOLLOWER:
                self._step_down_locked(term)
            self.leader_id = leader_id
            self._election_deadline = self._random_deadline()
            self._last_contact = time.monotonic()
            # a FRESH node (empty log, no snapshot) joining an established
            # cluster: everything already committed is pre-join history —
            # its peer set came from gossip bootstrap, so historical
            # PEER_REMOVE entries must not apply (the removed peer may
            # have long since rejoined)
            if (
                self._config_replay_boundary == 0
                and self._snapshot_index == 0
                and not self.log
            ):
                self._config_replay_boundary = leader_commit
            # prev below the snapshot boundary: the overlap is committed by
            # definition, but our term knowledge was compacted — hint the
            # snapshot index so the leader advances next_index past it (or
            # falls back to InstallSnapshot) instead of backing up forever
            if 0 < prev_index < self._snapshot_index:
                return [self.current_term, False, self._snapshot_index]
            # consistency check
            if prev_index > 0 and self._term_at(prev_index) != prev_term:
                return [self.current_term, False, min(self._last_index(), prev_index - 1)]
            for e in entries:
                index, e_term, entry_type, payload = e
                if index <= self._snapshot_index:
                    # covered by the snapshot — committed by definition;
                    # entering the truncation path here would compute a
                    # negative slice position and wipe the whole tail
                    continue
                existing = self._term_at(index)
                if existing == e_term:
                    continue  # already have it
                if existing != -1 or index <= self._last_index():
                    # conflict: truncate from here
                    pos = index - self._snapshot_index - 1
                    self.log = self.log[:max(pos, 0)]
                    if self.store is not None:
                        self.store.truncate_after(index)
                if index == self._last_index() + 1:
                    self._append_locked(index, e_term, entry_type, payload)
                else:
                    # gap (shouldn't happen): reject so the leader backs up
                    return [self.current_term, False, self._last_index()]
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit, self._last_index())
                self._apply_committed_locked()
            return [self.current_term, True, self._last_index()]

    def _handle_install_snapshot(self, term, leader_id, last_index, last_term,
                                 state_blob, config=None):
        with self._lock:
            if term < self.current_term:
                return self.current_term
            self._step_down_locked(term)
            self.leader_id = leader_id
            self._election_deadline = self._random_deadline()
            self._last_contact = time.monotonic()
            if last_index <= self._snapshot_index:
                return self.current_term
            if self._config_replay_boundary == 0:
                # snapshot install = joining established history (see
                # append-entries fresh-node boundary)
                self._config_replay_boundary = last_index
            self._snapshot_index = last_index
            self._snapshot_term = last_term
            self._snapshot_state = state_blob
            self._snapshot_config = config
            # membership as of the snapshot: peers whose PEER_ADD entries
            # were compacted arrive here
            self._apply_snapshot_config_locked(config)
            self.log = [e for e in self.log if e[0] > last_index]
            if self._snapshot_path is not None:
                # fsync before replace: the log truncation below discards
                # the entries this snapshot supersedes, so the snapshot
                # must be durable first or a crash loses committed state
                tmp = self._snapshot_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(codec_encode(
                        (last_index, last_term, state_blob, config)
                    ))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._snapshot_path)
            if self.store is not None:
                self.store.truncate_before(last_index + 1)
                self.store.sync()
            if self.fsm is not None:
                self.fsm.restore(_decode_fsm_state(state_blob))
            self.last_applied = last_index
            self.commit_index = max(self.commit_index, last_index)
            self._snapshots_installed += 1
            return self.current_term

    # -- introspection ---------------------------------------------------

    def last_contact_age_s(self) -> float:
        """Seconds since the last leader contact (AppendEntries /
        InstallSnapshot) — the follower_lag measure stale reads stamp
        into QueryMeta. 0 while leading (we ARE the contact)."""
        with self._lock:
            if self.state == LEADER:
                return 0.0
            return max(time.monotonic() - self._last_contact, 0.0)

    def stats(self, peer: int = 0) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "term": self.current_term,
                "leader_id": self.leader_id,
                "last_index": self._last_index(),
                "commit_index": self.commit_index,
                "applied_index": self.last_applied,
                "num_peers": len(self.peers),
                "snapshot_index": self._snapshot_index,
                "snapshots_installed": self._snapshots_installed,
                "last_contact_age_s": (
                    0.0 if self.state == LEADER
                    else max(time.monotonic() - self._last_contact, 0.0)
                ),
            }
