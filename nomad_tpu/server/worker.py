"""Scheduling worker: dequeues evals, invokes a scheduler, submits plans.

Semantics follow reference ``nomad/worker.go`` — N workers per server
(leader and followers), each scheduling optimistically against a state
snapshot at least as fresh as the eval (SnapshotMinIndex, worker.go:228),
acting as the scheduler's Planner and Ack/Nacking the broker.
"""
from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..scheduler.scheduler import new_scheduler
from ..trace import context as _xcontext
from ..trace import lifecycle as _lifecycle
from ..utils import metrics, phases
from ..structs.structs import Evaluation, Plan, PlanResult
from .eval_broker import NotOutstandingError, TokenMismatchError
from .fsm import EVAL_UPDATE

BUILTIN_SCHEDULERS = ["service", "batch", "system"]
CORE_SCHEDULER = "_core"


class Worker:
    def __init__(self, server, worker_id: int) -> None:
        self.server = server
        self.id = worker_id
        self.logger = logging.getLogger(f"nomad_tpu.worker.{worker_id}")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # set per-eval while scheduling
        self._eval_token = ""
        self._snapshot_index = 0
        # True when submit_plan handed commit + ack to the async
        # applier (nomad_tpu/pipeline): the run loop must NOT ack —
        # the applier acks after the raft commit lands
        self._handed_off = False
        # follower mode: RPC connection to the leader's broker/plan queue
        from ..rpc.transport import LeaderConn

        self._remote = LeaderConn(
            timeout=30.0, tls=getattr(server, "rpc_tls", None)
        )
        self._active_remote = None
        self.stats = {"evals_processed": 0, "plans_submitted": 0, "nacks": 0}
        # what this worker is doing RIGHT NOW — {eval_id, phase, since} or
        # None when idle; single-writer (the worker thread), read racily
        # by the liveness watchdog's dump
        self.current: Optional[Dict[str, object]] = None

    @contextmanager
    def _span(self, phase: str, eval_id: str):
        """Mark the worker's current span for the watchdog; restores the
        enclosing span on exit so nesting (submit inside invoke) works."""
        prev = self.current
        self.current = {
            "eval_id": eval_id, "phase": phase, "since": time.monotonic()
        }
        try:
            yield
        finally:
            self.current = prev

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._close_remote()

    # ------------------------------------------------------------------

    # -- remote (follower) mode ------------------------------------------
    # Followers run schedulers too (worker.go runs on every server): they
    # dequeue from the LEADER's broker and submit plans to its queue over
    # RPC, scheduling against their own replicated state snapshot.

    def _leader_rpc(self):
        """RPC client to the current leader, or None when we are the
        leader / no leader is known. Reconnects on leader change."""
        if self.server.is_leader:
            self._close_remote()
            return None
        get_addr = getattr(self.server, "get_leader_rpc_addr", None)
        addr = get_addr() if get_addr is not None else None
        if not addr:
            self._close_remote()
            return None
        return self._remote.get(addr)

    def _close_remote(self) -> None:
        self._remote.close()

    @staticmethod
    def _map_remote_error(e) -> None:
        """Benign broker token races cross the wire as error strings;
        re-raise them as their local exception types so the run loop's
        handling stays identical in both modes."""
        msg = str(e)
        if "NotOutstandingError" in msg:
            raise NotOutstandingError(msg) from e
        if "TokenMismatchError" in msg or "token mismatch" in msg:
            raise TokenMismatchError(msg) from e
        raise e

    def _run(self) -> None:
        schedulers = BUILTIN_SCHEDULERS + [CORE_SCHEDULER]
        # Coalesced idle accounting: consecutive empty dequeues accumulate
        # into ONE pending period, flushed as a single lifecycle IDLE_STAGE
        # span when work finally arrives. One span per busy->idle->busy
        # transition keeps the span ring at O(transitions) regardless of
        # poll cadence, and gives attribution direct evidence for the
        # "workers alive but starved" residual instead of an unattributed
        # hole (r05's invisible 498s).
        idle_t0: Optional[float] = None
        while not self._stop.is_set():
            try:
                remote = self._leader_rpc()
            except Exception:  # noqa: BLE001
                remote = None
            self._active_remote = remote
            poll_t0 = _lifecycle.pipeline_now()
            try:
                if remote is not None:
                    # core (GC) evals mutate raft directly and only run on
                    # the leader; followers dequeue the builtin types only
                    evaluation, token = remote.call(
                        "Eval.Dequeue", BUILTIN_SCHEDULERS, 1.0, no_forward=True
                    )
                    token = token or ""
                else:
                    evaluation, token = self.server.eval_broker.dequeue(
                        schedulers, timeout=0.25
                    )
            except Exception:  # noqa: BLE001 — leader gone mid-poll
                self._close_remote()
                self._stop.wait(0.5)
                continue
            if evaluation is None:
                if idle_t0 is None:
                    idle_t0 = poll_t0
                if remote is not None:
                    self._stop.wait(0.1)
                continue
            if idle_t0 is not None:
                _lifecycle.pipeline_record(
                    _lifecycle.IDLE_STAGE, f"worker-{self.id}",
                    idle_t0, _lifecycle.pipeline_now(),
                )
                idle_t0 = None
            metrics.incr_counter("nomad.worker.dequeue_eval")
            _lifecycle.on_worker(evaluation.id, self.id)
            self._eval_token = token
            self._handed_off = False
            # re-enter the eval's distributed trace (carried in
            # Evaluation.trace_ctx across raft AND the Eval.Dequeue wire
            # hop): outbound RPCs below — Plan.Submit, Eval.Ack — become
            # children of the span that created the eval
            trace_token = _xcontext.activate(
                getattr(evaluation, "trace_ctx", None)
            )
            try:
                # worker_busy is the coverage denominator: everything the
                # worker does between dequeue and ack should be explained
                # by some fine phase (phases.coverage)
                with phases.track("worker_busy"):
                    self._process(evaluation, token)
                if not self._handed_off:
                    self._ack(evaluation.id, token)
                self.stats["evals_processed"] += 1
            except (NotOutstandingError, TokenMismatchError):
                pass
            except Exception:  # noqa: BLE001
                self.logger.exception("eval %s failed", evaluation.id)
                self.stats["nacks"] += 1
                try:
                    self._nack(evaluation.id, token)
                except Exception:  # noqa: BLE001
                    pass
            finally:
                _xcontext.deactivate(trace_token)

    def _ack(self, eval_id: str, token: str) -> None:
        if self._active_remote is not None:
            from ..rpc.transport import RPCError

            try:
                self._active_remote.call("Eval.Ack", eval_id, token, no_forward=True)
            except RPCError as e:
                self._map_remote_error(e)
        else:
            self.server.eval_broker.ack(eval_id, token)

    def _nack(self, eval_id: str, token: str) -> None:
        if self._active_remote is not None:
            from ..rpc.transport import RPCError

            try:
                self._active_remote.call("Eval.Nack", eval_id, token, no_forward=True)
            except RPCError as e:
                self._map_remote_error(e)
        else:
            self.server.eval_broker.nack(eval_id, token)

    def _process(self, evaluation: Evaluation, token: str) -> None:
        if evaluation.type == CORE_SCHEDULER:
            from .core_sched import CoreScheduler

            snapshot = self.server.fsm.state.snapshot_min_index(
                max(evaluation.modify_index, evaluation.snapshot_index)
            )
            CoreScheduler(self.server, snapshot).process(evaluation)
            return

        from ..utils.hostwork import HOST_WORK_SEM

        # worker-side spans are emitted HERE, in the worker's process:
        # in follower mode the leader's lifecycle record never sees these
        # stamps, and the stitched trace is the only place the invoke
        # appears at all. role tags feed the follower_lag component.
        trace_id, trace_parent = _lifecycle.eval_trace_ids(
            evaluation.id, getattr(evaluation, "trace_ctx", None)
        )
        span_attrs = {
            "eval_id": evaluation.id, "worker": self.id,
            "role": "follower" if self._active_remote is not None
            else "leader",
        }

        wait_index = max(evaluation.modify_index, evaluation.snapshot_index)
        start = metrics.now()
        with self._span("wait_for_index", evaluation.id):
            # wait for the raft index WITHOUT the host-work permit (it can
            # block seconds); the snapshot COPY is a pure-GIL table clone —
            # park excess threads for that part only
            wait_t0 = _lifecycle.pipeline_now()
            with phases.track("wait_index"):
                self.server.fsm.state.wait_min_index(wait_index)
            # per-eval SnapshotMinIndex wait span on the lifecycle clock:
            # the attribution engine joins these against the wave windows
            # ("wait_min_index: 41% of makespan" names this exact block)
            wait_t1 = _lifecycle.pipeline_now()
            _lifecycle.pipeline_record(
                "wait_min_index", evaluation.id, wait_t0, wait_t1,
            )
            _xcontext.record_span(
                "eval.wait_min_index",
                _xcontext.wall_from_monotonic(wait_t0),
                _xcontext.wall_from_monotonic(wait_t1),
                trace_id=trace_id, parent_id=trace_parent,
                attrs=span_attrs,
            )
            with HOST_WORK_SEM:
                with phases.track("snapshot"):
                    # read-only shared view: a burst of evals at one state
                    # version shares one table clone (schedulers never
                    # mutate their snapshot; the plan applier, which does,
                    # takes private ones)
                    snapshot = self.server.fsm.state.shared_snapshot_min_index(
                        wait_index
                    )
        metrics.measure_since("nomad.worker.wait_for_index", start)
        self._snapshot_index = snapshot.latest_index
        sched = new_scheduler(evaluation.type, self.logger, snapshot, self)
        if hasattr(sched, "deterministic"):
            sched.deterministic = self.server.config.deterministic
        if hasattr(sched, "ring_decorrelate"):
            sched.ring_decorrelate = getattr(
                self.server.config, "ring_decorrelate", True
            )
        if hasattr(sched, "device_min_placements"):
            sched.device_min_placements = getattr(
                self.server.config, "device_min_placements", 0
            )
        start = metrics.now()
        _lifecycle.on_invoke_start(evaluation.id)
        invoke_t0 = _lifecycle.pipeline_now()
        try:
            with self._span("invoke_scheduler", evaluation.id):
                sched.process(evaluation)
        finally:
            _lifecycle.on_invoke_end(evaluation.id)
            _xcontext.record_span(
                "eval.invoke",
                _xcontext.wall_from_monotonic(invoke_t0),
                _xcontext.wall_from_monotonic(_lifecycle.pipeline_now()),
                trace_id=trace_id, parent_id=trace_parent,
                attrs=span_attrs,
            )
        metrics.measure_since(
            f"nomad.worker.invoke_scheduler.{evaluation.type}", start
        )

    # -- Planner protocol ------------------------------------------------

    @property
    def device_batcher(self):
        """The server's eval-batcher: schedulers route their placement
        scans through it so concurrent evals share one device dispatch
        (works identically in leader and follower mode — scheduling is
        local; only plan submission crosses the wire)."""
        return getattr(self.server, "device_batcher", None)

    @property
    def pipeline(self):
        """The leader-local async applier (nomad_tpu/pipeline), or None
        in follower mode — a follower's plan submission crosses the wire
        and must stay synchronous (the leader-side handler owns the
        response)."""
        if self._active_remote is not None:
            return None
        return getattr(self.server, "pipeline", None)

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        plan.eval_token = self._eval_token
        # stamp the snapshot the scheduler actually saw (worker.go:277), not
        # the newest index — the plan applier uses this to decide how much
        # optimistic re-validation the plan needs
        plan.snapshot_index = self._snapshot_index
        _lifecycle.on_plan_submit(plan.eval_id)
        if self._active_remote is not None:
            # the leader-side handler waits up to 60s on the plan queue;
            # the socket must outlast it, and a resend would enqueue the
            # plan twice — fail instead
            result: PlanResult = self._active_remote.call(
                "Plan.Submit", plan, no_forward=True, timeout=90.0, no_retry=True
            )
        else:
            pipe = self.pipeline
            if pipe is not None and pipe.try_submit(plan, self._eval_token):
                # Async handoff (nomad_tpu/pipeline): the applier owns
                # commit + ack from here; this worker thread goes straight
                # back to the broker so wave N+1's encode overlaps wave
                # N's evaluate/commit tail. The scheduler sees the plan's
                # own placements as a full-commit result — the optimistic
                # contract; a partial commit comes back later as a
                # re-dispatch or broker redelivery, both of which
                # reconcile against fresh state.
                self._handed_off = True
                metrics.incr_counter("nomad.worker.async_handoff")
                result = PlanResult(dense_placements=plan.dense_placements)
            else:
                self.server.eval_broker.pause_nack_timeout(
                    plan.eval_id, self._eval_token
                )
                try:
                    with self._span("submit_plan", plan.eval_id):
                        with phases.track("plan_submit"):
                            pending = self.server.plan_queue.enqueue(plan)
                            result = pending.future.result(timeout=60)
                finally:
                    try:
                        self.server.eval_broker.resume_nack_timeout(
                            plan.eval_id, self._eval_token
                        )
                    except (NotOutstandingError, TokenMismatchError):
                        pass
        self.stats["plans_submitted"] += 1

        srv = self.server
        if (
            not getattr(srv, "_first_job_latency_recorded", True)
            and srv._first_job_t0 is not None
            and not result.is_noop()
        ):
            # first plan commit after the first registration: the boot-
            # warmup latency the operator actually feels (VERDICT r3 #3)
            import time as _time

            srv._first_job_latency_recorded = True
            metrics.set_gauge(
                "nomad.server.first_job_latency_ms",
                (_time.monotonic() - srv._first_job_t0) * 1000.0,
            )

        if result.refresh_index:
            # the follower's replicated state catches up to the leader's
            # commit; schedulers always refresh from LOCAL state
            # (read-only shared view — see _process)
            new_state = self.server.fsm.state.shared_snapshot_min_index(
                result.refresh_index
            )
            self._snapshot_index = new_state.latest_index
            return result, new_state
        return result, None

    def update_eval(self, evaluation: Evaluation) -> None:
        evaluation.update_modify_time()
        if self._active_remote is not None:
            self._active_remote.call("Eval.Update", [evaluation], no_forward=True)
            return
        self.server.raft_apply(EVAL_UPDATE, [evaluation])

    def create_eval(self, evaluation: Evaluation) -> None:
        # Stamp the worker's snapshot index (worker.go:385): the blocked-
        # evals tracker compares it against per-class unblock indexes, and
        # without it every new blocked eval looks like it "missed" an old
        # unblock and is re-enqueued forever.
        if not evaluation.snapshot_index:
            evaluation.snapshot_index = self._snapshot_index
        evaluation.update_modify_time()
        if self._active_remote is not None:
            self._active_remote.call("Eval.Update", [evaluation], no_forward=True)
            return
        self.server.raft_apply(EVAL_UPDATE, [evaluation])

    def reblock_eval(self, evaluation: Evaluation) -> None:
        # Update in raft so a leader change re-blocks it, then re-insert
        # into the in-memory tracker (reference worker.go:426).
        if self._active_remote is not None:
            from ..rpc.transport import RPCError

            evaluation.update_modify_time()
            try:
                self._active_remote.call(
                    "Eval.Reblock", evaluation, self._eval_token, no_forward=True
                )
            except RPCError as e:
                self._map_remote_error(e)
            return
        token = self.server.eval_broker.outstanding(evaluation.id)
        if token != self._eval_token:
            raise TokenMismatchError(evaluation.id)
        evaluation.update_modify_time()
        self.server.raft_apply(EVAL_UPDATE, [evaluation])
        # Pass the delivery token: the eval is still outstanding in the
        # broker, so an unblock racing this worker's ack must requeue
        # through the ack path rather than be dropped as a duplicate. The
        # raft apply above already captured the eval via the FSM hook
        # (empty token); reblock records the token on that entry.
        self.server.blocked_evals.reblock(evaluation, token)
