"""Scheduling worker: dequeues evals, invokes a scheduler, submits plans.

Semantics follow reference ``nomad/worker.go`` — N workers per server
(leader and followers), each scheduling optimistically against a state
snapshot at least as fresh as the eval (SnapshotMinIndex, worker.go:228),
acting as the scheduler's Planner and Ack/Nacking the broker.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..scheduler.scheduler import new_scheduler
from ..utils import metrics
from ..structs.structs import Evaluation, Plan, PlanResult
from .eval_broker import NotOutstandingError, TokenMismatchError
from .fsm import EVAL_UPDATE

BUILTIN_SCHEDULERS = ["service", "batch", "system"]
CORE_SCHEDULER = "_core"


class Worker:
    def __init__(self, server, worker_id: int) -> None:
        self.server = server
        self.id = worker_id
        self.logger = logging.getLogger(f"nomad_tpu.worker.{worker_id}")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # set per-eval while scheduling
        self._eval_token = ""
        self._snapshot_index = 0
        self.stats = {"evals_processed": 0, "plans_submitted": 0, "nacks": 0}

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # ------------------------------------------------------------------

    def _run(self) -> None:
        schedulers = BUILTIN_SCHEDULERS + [CORE_SCHEDULER]
        while not self._stop.is_set():
            evaluation, token = self.server.eval_broker.dequeue(schedulers, timeout=0.25)
            if evaluation is None:
                continue
            metrics.incr_counter("nomad.worker.dequeue_eval")
            self._eval_token = token
            try:
                self._process(evaluation, token)
                self.server.eval_broker.ack(evaluation.id, token)
                self.stats["evals_processed"] += 1
            except (NotOutstandingError, TokenMismatchError):
                pass
            except Exception:  # noqa: BLE001
                self.logger.exception("eval %s failed", evaluation.id)
                self.stats["nacks"] += 1
                try:
                    self.server.eval_broker.nack(evaluation.id, token)
                except (NotOutstandingError, TokenMismatchError):
                    pass

    def _process(self, evaluation: Evaluation, token: str) -> None:
        if evaluation.type == CORE_SCHEDULER:
            from .core_sched import CoreScheduler

            snapshot = self.server.fsm.state.snapshot_min_index(
                max(evaluation.modify_index, evaluation.snapshot_index)
            )
            CoreScheduler(self.server, snapshot).process(evaluation)
            return

        wait_index = max(evaluation.modify_index, evaluation.snapshot_index)
        start = metrics.now()
        snapshot = self.server.fsm.state.snapshot_min_index(wait_index)
        metrics.measure_since("nomad.worker.wait_for_index", start)
        self._snapshot_index = snapshot.latest_index
        sched = new_scheduler(evaluation.type, self.logger, snapshot, self)
        if hasattr(sched, "deterministic"):
            sched.deterministic = self.server.config.deterministic
        start = metrics.now()
        sched.process(evaluation)
        metrics.measure_since(
            f"nomad.worker.invoke_scheduler.{evaluation.type}", start
        )

    # -- Planner protocol ------------------------------------------------

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        plan.eval_token = self._eval_token
        # stamp the snapshot the scheduler actually saw (worker.go:277), not
        # the newest index — the plan applier uses this to decide how much
        # optimistic re-validation the plan needs
        plan.snapshot_index = self._snapshot_index
        self.server.eval_broker.pause_nack_timeout(plan.eval_id, self._eval_token)
        try:
            pending = self.server.plan_queue.enqueue(plan)
            result: PlanResult = pending.future.result(timeout=60)
        finally:
            try:
                self.server.eval_broker.resume_nack_timeout(plan.eval_id, self._eval_token)
            except (NotOutstandingError, TokenMismatchError):
                pass
        self.stats["plans_submitted"] += 1

        if result.refresh_index:
            new_state = self.server.fsm.state.snapshot_min_index(result.refresh_index)
            self._snapshot_index = new_state.latest_index
            return result, new_state
        return result, None

    def update_eval(self, evaluation: Evaluation) -> None:
        evaluation.update_modify_time()
        self.server.raft_apply(EVAL_UPDATE, [evaluation])

    def create_eval(self, evaluation: Evaluation) -> None:
        # Stamp the worker's snapshot index (worker.go:385): the blocked-
        # evals tracker compares it against per-class unblock indexes, and
        # without it every new blocked eval looks like it "missed" an old
        # unblock and is re-enqueued forever.
        if not evaluation.snapshot_index:
            evaluation.snapshot_index = self._snapshot_index
        evaluation.update_modify_time()
        self.server.raft_apply(EVAL_UPDATE, [evaluation])

    def reblock_eval(self, evaluation: Evaluation) -> None:
        # Update in raft so a leader change re-blocks it, then re-insert
        # into the in-memory tracker (reference worker.go:426).
        token = self.server.eval_broker.outstanding(evaluation.id)
        if token != self._eval_token:
            raise TokenMismatchError(evaluation.id)
        evaluation.update_modify_time()
        self.server.raft_apply(EVAL_UPDATE, [evaluation])
        # Pass the delivery token: the eval is still outstanding in the
        # broker, so an unblock racing this worker's ack must requeue
        # through the ack path rather than be dropped as a duplicate. The
        # raft apply above already captured the eval via the FSM hook
        # (empty token); reblock records the token on that entry.
        self.server.blocked_evals.reblock(evaluation, token)
