"""State store (reference nomad/state/)."""
from .state_store import StateStore  # noqa: F401
