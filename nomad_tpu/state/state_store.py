"""In-memory state store with Raft-index stamps.

Fills the role of reference ``nomad/state/state_store.go`` (go-memdb MVCC).
Instead of an immutable radix tree, this design keeps simple dict tables
guarded by an RWLock with copy-on-snapshot: schedulers always work against a
``snapshot()`` (cheap shallow copies of table dicts), so writers never
invalidate a running evaluation — the same isolation guarantee memdb gives
the reference. Blocking queries are exposed via a table-version condition
variable (reference state_store.go:188 BlockingQuery).
"""
from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import phases as _phases
from ..structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_STOP,
    EVAL_STATUS_BLOCKED,
    JOB_STATUS_DEAD,
    JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING,
    Allocation,
    Deployment,
    Evaluation,
    Job,
    Node,
    PlanResult,
    SchedulerConfiguration,
)
from ..utils.lock_witness import witness_rlock


class StateStore:
    def __init__(self) -> None:
        self._lock = witness_rlock("state_store.StateStore._lock")
        self._cond = threading.Condition(self._lock)
        self.latest_index = 0

        # (store_id, node_epoch) keys the encode layer's static-node-array
        # caches: the epoch bumps on every node write, so snapshots taken
        # between node changes share one dense encoding of the fleet.
        import uuid as _uuid

        self.store_id = _uuid.uuid4().hex
        self.node_epoch = 0
        # bumps on every capacity-relevant write (node tables, alloc
        # upserts, dense block inserts, client syncs): the plan applier
        # keeps its optimistic snapshot alive across plans while this
        # matches its prediction, instead of re-snapshotting per plan
        self.capacity_epoch = 0
        # bumps on ALLOC-derived writes only (alloc upserts, client
        # syncs, dense blocks, eval-GC alloc deletes) — NOT on job or
        # node writes. (store_id, node_epoch, usage_epoch) keys the
        # encode layer's whole-eval cache: a burst of job registrations
        # must not invalidate encodings whose usage inputs are unchanged
        self.usage_epoch = 0
        # last snapshot served by shared_snapshot_min_index (read-only
        # consumers; replaced whenever the live version moves past it)
        self._shared_snap: Optional["StateStore"] = None
        # callers currently blocked in a *min_index wait (flight-recorder
        # probe: the SnapshotMinIndex stall surface)
        self._min_index_waiters = 0

        self.nodes_table: Dict[str, Node] = {}
        self.jobs_table: Dict[Tuple[str, str], Job] = {}
        self.job_versions: Dict[Tuple[str, str], List[Job]] = {}
        self.allocs_table: Dict[str, Allocation] = {}
        self.evals_table: Dict[str, Evaluation] = {}
        self.deployments_table: Dict[str, Deployment] = {}
        # (namespace, parent job id) -> last launch time ns (reference
        # schema.go periodic_launch table)
        self.periodic_launch_table: Dict[Tuple[str, str], int] = {}
        self.scheduler_config_entry: Optional[SchedulerConfiguration] = None
        self.autopilot_config_entry = None  # server.autopilot.AutopilotConfig
        # ACL tables (reference schema.go acl_policy / acl_token)
        self.acl_policies_table: Dict[str, "ACLPolicy"] = {}
        self.acl_tokens_table: Dict[str, "ACLToken"] = {}  # by accessor
        self._tokens_by_secret: Dict[str, str] = {}  # secret -> accessor
        self.acl_bootstrap_index = 0
        # alloc id -> [{"task", "accessor"}] (reference schema.go
        # vault_accessors table)
        self.vault_accessors_table: Dict[str, list] = {}

        # Incremental per-node usage mirror: node_id -> (cpu, mem, disk,
        # mbits) summed over NON-terminal allocs, updated on every alloc
        # write. Rows are immutable tuples, so snapshots share them via a
        # shallow dict copy. Consumed by the TPU encode layer, replacing
        # O(nodes) per-eval queries with an O(1) lookup per node.
        self._node_usage: Dict[str, tuple] = {}

        # secondary indexes
        self._allocs_by_node: Dict[str, set] = {}
        self._allocs_by_job: Dict[Tuple[str, str], set] = {}
        self._allocs_by_eval: Dict[str, set] = {}
        self._evals_by_job: Dict[Tuple[str, str], set] = {}
        self._deployments_by_job: Dict[Tuple[str, str], set] = {}
        # (namespace, parent job id) -> child job ids (periodic/dispatch)
        self._jobs_by_parent: Dict[Tuple[str, str], set] = {}

        # Dense placement blocks (structs.DenseTGPlacements): allocs
        # committed by the TPU engine's dense path live here as parallel
        # arrays; Allocation objects materialize lazily on read. Indexes
        # are BLOCK-level (one entry per block, not per alloc) except the
        # id map. An id in ``_dense_superseded`` has been overwritten by a
        # regular alloc write (client sync, stop, GC) and its table entry
        # is authoritative; the block slot is dead.
        self._dense_blocks: List = []
        self._dense_by_id: Dict[str, tuple] = {}  # id -> (block, i)
        self._dense_by_job: Dict[Tuple[str, str], list] = {}
        self._dense_by_node: Dict[str, list] = {}
        self._dense_by_eval: Dict[str, list] = {}
        self._dense_superseded: set = set()
        # block key -> superseded-slot count; a fully-dead block (every
        # slot rewritten as a table alloc) is compacted away entirely
        self._dense_dead: Dict[str, int] = {}

    # pickling (raft snapshot persistence): locks are recreated on load.
    # Dense secondary indexes are DERIVED from _dense_blocks and dropped:
    # the snapshot codec has no shared-reference dedup, so serializing
    # _dense_by_id would re-encode every block once per contained alloc.
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        d.pop("_cond", None)
        d.pop("_shared_snap", None)
        d.pop("_min_index_waiters", None)
        d.pop("_dense_by_id", None)
        d.pop("_dense_by_job", None)
        d.pop("_dense_by_node", None)
        d.pop("_dense_by_eval", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = witness_rlock("state_store.StateStore._lock")
        self._cond = threading.Condition(self._lock)
        # fresh identity: a restored store may diverge from its origin, so
        # it must never share the origin's encode-cache key space
        import uuid as _uuid

        self.store_id = _uuid.uuid4().hex
        if "node_epoch" not in self.__dict__:
            self.node_epoch = 0
        if "capacity_epoch" not in self.__dict__:
            self.capacity_epoch = 0
        if "usage_epoch" not in self.__dict__:
            self.usage_epoch = 0
        self._shared_snap = None
        self._min_index_waiters = 0
        # Pickles from pre-mirror builds lack the usage mirror: rebuild it
        # from the alloc table so writes and snapshots keep working.
        # pre-dense snapshots lack the dense tables entirely; fresh ones
        # carry _dense_blocks (+ superseded set) and the derived indexes
        # rebuild here
        if "_dense_blocks" not in self.__dict__:
            self._dense_blocks = []
            self._dense_superseded = set()
        self._dense_by_id = {}
        self._dense_by_job = {}
        self._dense_by_node = {}
        self._dense_by_eval = {}
        self._dense_dead = {}
        live_blocks = []
        for block in self._dense_blocks:
            dead = sum(1 for aid in block.ids if aid in self._dense_superseded)
            if dead >= len(block.ids):
                # fully superseded: compact at load
                for aid in block.ids:
                    self._dense_superseded.discard(aid)
                continue
            live_blocks.append(block)
            if dead:
                self._dense_dead[block.key()] = dead
            self._index_dense_block_locked(block)
        self._dense_blocks = live_blocks
        if "_node_usage" not in self.__dict__:
            from ..structs.funcs import alloc_usage_vec

            usage: Dict[str, tuple] = {}
            for alloc in self.allocs_table.values():
                if alloc.terminal_status():
                    continue
                u = alloc_usage_vec(alloc)
                row = usage.get(alloc.node_id, (0.0, 0.0, 0.0, 0.0))
                usage[alloc.node_id] = (
                    row[0] + u[0], row[1] + u[1], row[2] + u[2], row[3] + u[3]
                )
            self._node_usage = usage

    # ------------------------------------------------------------------
    # snapshots / blocking
    # ------------------------------------------------------------------

    def snapshot(self) -> "StateStore":
        """Point-in-time view; shallow-copies tables (objects are treated as
        immutable once inserted — all writers insert copies)."""
        with self._lock:
            snap = StateStore.__new__(StateStore)
            snap._lock = witness_rlock("state_store.StateStore._lock")
            snap._cond = threading.Condition(snap._lock)
            snap.latest_index = self.latest_index
            snap.store_id = self.store_id
            snap.node_epoch = self.node_epoch
            snap.capacity_epoch = self.capacity_epoch
            snap.usage_epoch = self.usage_epoch
            snap._shared_snap = None
            snap._min_index_waiters = 0
            snap.nodes_table = dict(self.nodes_table)
            snap.jobs_table = dict(self.jobs_table)
            snap.job_versions = {k: list(v) for k, v in self.job_versions.items()}
            snap.allocs_table = dict(self.allocs_table)
            snap.evals_table = dict(self.evals_table)
            snap.deployments_table = dict(self.deployments_table)
            snap.periodic_launch_table = dict(self.periodic_launch_table)
            snap.scheduler_config_entry = self.scheduler_config_entry
            snap.autopilot_config_entry = self.autopilot_config_entry
            snap.acl_policies_table = dict(self.acl_policies_table)
            snap.acl_tokens_table = dict(self.acl_tokens_table)
            snap._tokens_by_secret = dict(self._tokens_by_secret)
            snap.acl_bootstrap_index = self.acl_bootstrap_index
            snap.vault_accessors_table = {
                k: list(v) for k, v in self.vault_accessors_table.items()
            }
            snap._node_usage = dict(self._node_usage)  # rows are immutable
            # dense: blocks are immutable-once-committed and shared;
            # containers are copied so inserts never cross stores.
            # _dense_by_id is NOT copied (it can reach alloc-count size —
            # copying it per snapshot would tax every eval): snapshots
            # carry None and resolve ids by scanning their block list
            # through the per-block id_index_map caches.
            snap._dense_blocks = list(self._dense_blocks)
            snap._dense_by_id = None
            snap._dense_by_job = {k: list(v) for k, v in self._dense_by_job.items()}
            snap._dense_by_node = {k: list(v) for k, v in self._dense_by_node.items()}
            snap._dense_by_eval = {k: list(v) for k, v in self._dense_by_eval.items()}
            snap._dense_superseded = set(self._dense_superseded)
            snap._dense_dead = dict(self._dense_dead)
            snap._allocs_by_node = {k: set(v) for k, v in self._allocs_by_node.items()}
            snap._allocs_by_job = {k: set(v) for k, v in self._allocs_by_job.items()}
            snap._allocs_by_eval = {k: set(v) for k, v in self._allocs_by_eval.items()}
            snap._evals_by_job = {k: set(v) for k, v in self._evals_by_job.items()}
            snap._deployments_by_job = {k: set(v) for k, v in self._deployments_by_job.items()}
            snap._jobs_by_parent = {k: set(v) for k, v in self._jobs_by_parent.items()}
            return snap

    def _wait_for_index_locked(self, index: int, timeout: float) -> None:
        """Shared wait body (callers hold self._cond); tracks the waiter
        count the flight recorder probes."""
        self._min_index_waiters += 1
        try:
            if not self._cond.wait_for(
                lambda: self.latest_index >= index, timeout=timeout
            ):
                raise TimeoutError(
                    f"timed out waiting for index {index} (at {self.latest_index})"
                )
        finally:
            self._min_index_waiters -= 1

    def min_index_waiters(self) -> int:
        """Callers currently blocked waiting for an applied index."""
        return getattr(self, "_min_index_waiters", 0)

    def wait_min_index(self, index: int, timeout: float = 5.0) -> None:
        """Block until the store has applied ``index`` (no snapshot)."""
        with self._cond:
            self._wait_for_index_locked(index, timeout)

    def snapshot_min_index(self, index: int, timeout: float = 5.0) -> "StateStore":
        """Wait until the store has applied ``index`` then snapshot
        (reference state_store.go:114)."""
        with self._cond:
            self._wait_for_index_locked(index, timeout)
            return self.snapshot()

    def shared_snapshot_min_index(
        self, index: int, timeout: float = 5.0
    ) -> "StateStore":
        """Read-only variant of ``snapshot_min_index`` that SHARES one
        snapshot object across callers at the same state version.

        SnapshotMinIndex semantics only require a point-in-time view at
        or after ``index``; any cached snapshot whose latest_index
        satisfies that is a valid answer, so a burst of evals at one
        state version shares ONE table clone instead of cloning per
        eval (the clone is a pure-GIL cost at C1M eval rates).

        Callers MUST treat the result as read-only — the plan applier,
        which folds optimistic results into its snapshot, must keep
        using ``snapshot_min_index``."""
        with self._cond:
            self._wait_for_index_locked(index, timeout)
            cached = self._shared_snap
            # serve the cached view only while it matches the LIVE
            # version: a fresher-than-requested-but-stale-vs-live view
            # would be legal, but serving current state keeps scheduling
            # quality identical to the uncached behavior
            if cached is not None and cached.latest_index == self.latest_index:
                return cached
            snap = self.snapshot()
            self._shared_snap = snap
            return snap

    def blocking_query(
        self, run: Callable[["StateStore"], object], min_index: int, timeout: float = 60.0
    ):
        """Re-run ``run`` once the store passes ``min_index`` (long poll)."""
        with self._cond:
            self._cond.wait_for(lambda: self.latest_index > min_index, timeout=timeout)
            return run(self), self.latest_index

    def read_with_index(self, run: Callable[["StateStore"], object]):
        """Run a read and capture ``latest_index`` under ONE lock hold, so
        the returned index is exactly the version the result reflects — a
        write landing between the query and a separate index read would
        otherwise be falsely covered by the stamped index, and a client
        chaining it as ``min_query_index`` would never see that write
        (the watch layer's QueryMeta stamping relies on this)."""
        with self._lock:
            return run(self), self.latest_index

    def _bump(self, index: Optional[int] = None) -> int:
        if index is None:
            index = self.latest_index + 1
        self.latest_index = max(self.latest_index, index)
        self._cond.notify_all()
        return index

    # ------------------------------------------------------------------
    # nodes
    # ------------------------------------------------------------------

    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            node = node.copy()  # snapshot isolation: the store owns its objects
            existing = self.nodes_table.get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
                # Preserve operator-set fields across re-registration
                node.drain = existing.drain
                node.scheduling_eligibility = existing.scheduling_eligibility
            else:
                node.create_index = index
            node.modify_index = index
            if not node.computed_class:
                node.compute_class()
            self.nodes_table[node.id] = node
            self.node_epoch += 1
            self.capacity_epoch += 1
            self._bump(index)

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            self.nodes_table.pop(node_id, None)
            self.node_epoch += 1
            self.capacity_epoch += 1
            self._bump(index)

    def update_node_status(self, index: int, node_id: str, status: str) -> None:
        with self._lock:
            node = self.nodes_table.get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            node = node.copy()
            node.status = status
            node.modify_index = index
            self.nodes_table[node_id] = node
            self.node_epoch += 1
            self.capacity_epoch += 1
            self._bump(index)

    def update_node_drain(
        self, index: int, node_id: str, drain, mark_eligible: bool = True
    ) -> None:
        """``drain`` is a DrainStrategy, True (default strategy), or a falsy
        value ending the drain. A completed drain leaves the node ineligible
        (reference nomad/drainer marks drain done without restoring
        eligibility); pass mark_eligible=True only for operator-initiated
        drain removal."""
        with self._lock:
            node = self.nodes_table.get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            from ..structs.structs import (
                NODE_SCHED_ELIGIBLE,
                NODE_SCHED_INELIGIBLE,
                DrainStrategy,
            )

            if drain is True:
                drain = DrainStrategy()
            node = node.copy()
            node.drain_strategy = drain or None
            node.drain = node.drain_strategy is not None
            if node.drain:
                node.scheduling_eligibility = NODE_SCHED_INELIGIBLE
            elif mark_eligible:
                node.scheduling_eligibility = NODE_SCHED_ELIGIBLE
            node.modify_index = index
            self.nodes_table[node_id] = node
            self.node_epoch += 1
            self.capacity_epoch += 1
            self._bump(index)

    def update_node_eligibility(self, index: int, node_id: str, eligibility: str) -> None:
        with self._lock:
            node = self.nodes_table.get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            node = node.copy()
            node.scheduling_eligibility = eligibility
            node.modify_index = index
            self.nodes_table[node_id] = node
            self.node_epoch += 1
            self.capacity_epoch += 1
            self._bump(index)

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self.nodes_table.get(node_id)

    def nodes(self) -> List[Node]:
        return list(self.nodes_table.values())

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            job = job.copy()  # snapshot isolation: the store owns its objects
            key = (job.namespace, job.id)
            existing = self.jobs_table.get(key)
            if existing is not None:
                job.create_index = existing.create_index
                job.modify_index = index
                job.job_modify_index = index
                job.version = existing.version + 1
            else:
                job.create_index = index
                job.modify_index = index
                job.job_modify_index = index
                job.version = 0
            if job.status not in (JOB_STATUS_PENDING, JOB_STATUS_RUNNING, JOB_STATUS_DEAD):
                job.status = JOB_STATUS_PENDING
            self.capacity_epoch += 1  # planner payloads read job state
            self.jobs_table[key] = job
            self.job_versions.setdefault(key, []).append(job)
            # keep a bounded version history (reference keeps 6)
            if len(self.job_versions[key]) > 6:
                self.job_versions[key] = self.job_versions[key][-6:]
            if job.parent_id:
                self._jobs_by_parent.setdefault(
                    (job.namespace, job.parent_id), set()
                ).add(job.id)
            self._bump(index)

    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            self.capacity_epoch += 1
            job = self.jobs_table.pop((namespace, job_id), None)
            self.job_versions.pop((namespace, job_id), None)
            self.periodic_launch_table.pop((namespace, job_id), None)
            if job is not None and job.parent_id:
                children = self._jobs_by_parent.get((namespace, job.parent_id))
                if children is not None:
                    children.discard(job_id)
            self._jobs_by_parent.pop((namespace, job_id), None)
            self._bump(index)

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self.jobs_table.get((namespace, job_id))

    def job_by_id_and_version(self, namespace: str, job_id: str, version: int) -> Optional[Job]:
        for j in self.job_versions.get((namespace, job_id), []):
            if j.version == version:
                return j
        return None

    def jobs_by_parent(self, namespace: str, parent_id: str) -> List[Job]:
        """Child jobs of a periodic/parameterized parent (indexed)."""
        with self._lock:
            ids = list(self._jobs_by_parent.get((namespace, parent_id), ()))
            return [
                j
                for j in (self.jobs_table.get((namespace, i)) for i in ids)
                if j is not None
            ]

    def jobs(self) -> List[Job]:
        return list(self.jobs_table.values())

    # ------------------------------------------------------------------
    # evals
    # ------------------------------------------------------------------

    def upsert_evals(self, index: int, evals: List[Evaluation]) -> None:
        with self._lock:
            for e in evals:
                e = e.copy()  # snapshot isolation: the store owns its objects
                existing = self.evals_table.get(e.id)
                if existing is not None:
                    e.create_index = existing.create_index
                else:
                    e.create_index = index
                e.modify_index = index
                self.evals_table[e.id] = e
                self._evals_by_job.setdefault((e.namespace, e.job_id), set()).add(e.id)
            self._bump(index)

    def delete_eval(self, index: int, eval_ids: List[str], alloc_ids: List[str]) -> None:
        with self._lock:
            for eid in eval_ids:
                e = self.evals_table.pop(eid, None)
                if e is not None:
                    s = self._evals_by_job.get((e.namespace, e.job_id))
                    if s is not None:
                        s.discard(eid)
            if alloc_ids:
                self.capacity_epoch += 1
                self.usage_epoch += 1
            for aid in alloc_ids:
                self._remove_alloc_index(aid)
                self.allocs_table.pop(aid, None)
            self._bump(index)

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self.evals_table.get(eval_id)

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return [
            self.evals_table[eid]
            for eid in self._evals_by_job.get((namespace, job_id), set())
            if eid in self.evals_table
        ]

    def evals(self) -> List[Evaluation]:
        return list(self.evals_table.values())

    # ------------------------------------------------------------------
    # allocs
    # ------------------------------------------------------------------

    def _usage_delta_locked(self, alloc: Allocation, sign: float) -> None:
        if alloc.terminal_status():
            return
        from ..structs.funcs import alloc_usage_vec

        u = alloc_usage_vec(alloc)
        row = self._node_usage.get(alloc.node_id)
        if row is None:
            row = (0.0, 0.0, 0.0, 0.0)
        self._node_usage[alloc.node_id] = (
            row[0] + sign * u[0], row[1] + sign * u[1],
            row[2] + sign * u[2], row[3] + sign * u[3],
        )

    def _index_alloc(self, alloc: Allocation) -> None:
        self._allocs_by_node.setdefault(alloc.node_id, set()).add(alloc.id)
        self._allocs_by_job.setdefault((alloc.namespace, alloc.job_id), set()).add(alloc.id)
        self._allocs_by_eval.setdefault(alloc.eval_id, set()).add(alloc.id)
        self._usage_delta_locked(alloc, +1.0)

    def _remove_alloc_index(self, alloc_id: str) -> None:
        alloc = self.allocs_table.get(alloc_id)
        if alloc is None:
            # a live dense slot is "removed" by superseding it
            self._supersede_dense(alloc_id)
            return
        self._allocs_by_node.get(alloc.node_id, set()).discard(alloc_id)
        self._allocs_by_job.get((alloc.namespace, alloc.job_id), set()).discard(alloc_id)
        self._allocs_by_eval.get(alloc.eval_id, set()).discard(alloc_id)
        self._usage_delta_locked(alloc, -1.0)

    # -- dense placement blocks -----------------------------------------

    def _index_dense_block_locked(self, block) -> None:
        """Secondary-index wiring for one block (insert + setstate
        rebuild share it; callers hold ``_lock`` — setstate runs before
        the store is published). The id map is skipped on snapshots
        (None)."""
        if self._dense_by_id is not None:
            for i, aid in enumerate(block.ids):
                self._dense_by_id[aid] = (block, i)
        self._dense_by_job.setdefault(
            (block.namespace, block.job_id), []
        ).append(block)
        if block.eval_id:
            self._dense_by_eval.setdefault(block.eval_id, []).append(block)
        for node_id in block.node_index_map():
            self._dense_by_node.setdefault(node_id, []).append(block)

    def _dense_lookup(self, alloc_id: str):
        """(block, i) for a dense id, superseded or not; None if unknown.
        The live store resolves through the eager id map; snapshots
        (which carry _dense_by_id=None to keep snapshotting O(blocks))
        scan their block list via the per-block id caches."""
        d = self._dense_by_id
        if d is not None:
            return d.get(alloc_id)
        for block in self._dense_blocks:
            i = block.id_index_map().get(alloc_id)
            if i is not None:
                return (block, i)
        return None

    def _supersede_dense(self, alloc_id: str) -> None:
        """Mark a dense slot dead (its id is being rewritten as a regular
        table alloc, or deleted) and return its usage to the mirror.
        Dense slots are non-terminal (desired=run, client=pending) until
        superseded, so the subtraction is unconditional."""
        entry = self._dense_lookup(alloc_id)
        if entry is None or alloc_id in self._dense_superseded:
            return
        block, i = entry
        self._dense_superseded.add(alloc_id)
        u = block.ask_vec
        node_id = block.node_ids[i]
        row = self._node_usage.get(node_id, (0.0, 0.0, 0.0, 0.0))
        self._node_usage[node_id] = (
            row[0] - u[0], row[1] - u[1], row[2] - u[2], row[3] - u[3]
        )
        key = block.key()
        dead = self._dense_dead.get(key, 0) + 1
        if dead >= len(block.ids):
            self._compact_dense_block(block)
        else:
            self._dense_dead[key] = dead

    def _compact_dense_block(self, block) -> None:
        """Every slot of the block has been superseded by a table alloc:
        drop the block from all containers so a long-lived store doesn't
        accumulate dead history (client syncs rewrite every alloc in
        steady state)."""
        self._dense_dead.pop(block.key(), None)
        for aid in block.ids:
            if self._dense_by_id is not None:
                self._dense_by_id.pop(aid, None)
            self._dense_superseded.discard(aid)
        self._dense_blocks = [b for b in self._dense_blocks if b is not block]
        jk = (block.namespace, block.job_id)
        lst = self._dense_by_job.get(jk)
        if lst is not None:
            lst[:] = [b for b in lst if b is not block]
            if not lst:
                del self._dense_by_job[jk]
        if block.eval_id:
            lst = self._dense_by_eval.get(block.eval_id)
            if lst is not None:
                lst[:] = [b for b in lst if b is not block]
                if not lst:
                    del self._dense_by_eval[block.eval_id]
        for node_id in block.node_index_map():
            lst = self._dense_by_node.get(node_id)
            if lst is not None:
                lst[:] = [b for b in lst if b is not block]
                if not lst:
                    del self._dense_by_node[node_id]

    def _existing_alloc(self, alloc_id: str) -> Optional[Allocation]:
        """Current version of an alloc for copy-on-write updates: the
        table entry, or the materialized live dense slot."""
        alloc = self.allocs_table.get(alloc_id)
        if alloc is not None:
            return alloc
        entry = self._dense_lookup(alloc_id)
        if entry is None or alloc_id in self._dense_superseded:
            return None
        block, i = entry
        return block.materialize(i)

    def _dense_materialize_live(self, blocks, predicate=None) -> List[Allocation]:
        """Materialize the live (non-superseded) slots of the given
        blocks, optionally filtered by ``predicate(block, i)``."""
        with _phases.track("dense_mat"):
            out: List[Allocation] = []
            superseded = self._dense_superseded
            for block in blocks:
                for i, aid in enumerate(block.ids):
                    if aid in superseded:
                        continue
                    if predicate is not None and not predicate(block, i):
                        continue
                    out.append(block.materialize(i))
            return out

    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        with self._lock:
            self._upsert_allocs_impl(index, allocs)
            self._bump(index)

    def _upsert_allocs_impl(self, index: int, allocs: List[Allocation]) -> None:
        if allocs:
            self.capacity_epoch += 1
            self.usage_epoch += 1
        for alloc in allocs:
            # Snapshot isolation: copy the alloc, sharing the (immutable) job.
            alloc = alloc.copy_skip_job()
            existing = self._existing_alloc(alloc.id)
            if existing is not None:
                alloc.create_index = existing.create_index
                alloc.create_time_ns = existing.create_time_ns
                # Client-owned fields survive server-side updates
                if alloc.client_status == "" and existing.client_status != "":
                    alloc.client_status = existing.client_status
                # table removal or dense supersede, as appropriate
                self._remove_alloc_index(alloc.id)
            else:
                alloc.create_index = index
            alloc.modify_index = index
            if alloc.job is None and existing is not None:
                alloc.job = existing.job
            self.allocs_table[alloc.id] = alloc
            self._index_alloc(alloc)

    def update_allocs_from_client(self, index: int, allocs: List[Allocation]) -> None:
        """Client status sync (reference state_store.go:1933)."""
        with self._lock:
            if allocs:
                self.capacity_epoch += 1
                self.usage_epoch += 1
            flips_by_deployment: Dict[str, List[Tuple[Optional[bool], Allocation]]] = {}
            for client_alloc in allocs:
                existing = self._existing_alloc(client_alloc.id)
                if existing is None:
                    continue
                prev_healthy = (
                    existing.deployment_status.healthy
                    if existing.deployment_status is not None
                    else None
                )
                updated = existing.copy_skip_job()
                updated.client_status = client_alloc.client_status
                updated.client_description = client_alloc.client_description
                updated.task_states = dict(client_alloc.task_states)
                # own the status object: never share with (or mutate) the
                # caller's payload. A sync carrying no deployment_status keeps
                # the recorded health — erasing it would orphan the counter
                # delta and let a re-report double-count.
                if client_alloc.deployment_status is not None:
                    updated.deployment_status = copy.deepcopy(
                        client_alloc.deployment_status
                    )
                updated.modify_index = index
                updated.modify_time_ns = client_alloc.modify_time_ns
                # A terminally failed alloc in a deployment counts as
                # unhealthy even if the client never reported health
                # (reference state_store.go: terminal status ⇒ unhealthy).
                if (
                    updated.deployment_id
                    and updated.client_status == ALLOC_CLIENT_FAILED
                    and (
                        updated.deployment_status is None
                        or updated.deployment_status.healthy is None
                    )
                ):
                    from ..structs.structs import AllocDeploymentStatus

                    if updated.deployment_status is None:
                        updated.deployment_status = AllocDeploymentStatus()
                    updated.deployment_status.healthy = False
                self._remove_alloc_index(existing.id)
                self.allocs_table[updated.id] = updated
                self._index_alloc(updated)
                if updated.deployment_id:
                    flips_by_deployment.setdefault(updated.deployment_id, []).append(
                        (prev_healthy, updated)
                    )
            for deployment_id, flips in flips_by_deployment.items():
                self._apply_health_deltas(index, deployment_id, flips)
            self._bump(index)

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        alloc = self.allocs_table.get(alloc_id)
        if alloc is not None:
            return alloc
        if self._dense_blocks:
            entry = self._dense_lookup(alloc_id)
            if entry is not None and alloc_id not in self._dense_superseded:
                return entry[0].materialize(entry[1])
        return None

    def allocs(self) -> List[Allocation]:
        out = list(self.allocs_table.values())
        if self._dense_blocks:
            out.extend(self._dense_materialize_live(self._dense_blocks))
        return out

    def count_allocs_desired_run(self) -> int:
        """O(table + blocks) count of desired_status == run — dense
        blocks count at block granularity (every live slot is run)."""
        from ..structs.structs import ALLOC_DESIRED_RUN

        with self._lock:
            n = sum(
                1 for a in self.allocs_table.values()
                if a.desired_status == ALLOC_DESIRED_RUN
            )
            n += sum(len(b.ids) for b in self._dense_blocks)
            n -= len(self._dense_superseded)
            return n

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        out = [
            self.allocs_table[aid]
            for aid in self._allocs_by_node.get(node_id, set())
            if aid in self.allocs_table
        ]
        blocks = self._dense_by_node.get(node_id)
        if blocks:
            # the per-node inline variant of _dense_materialize_live —
            # the C1M host-path hot loop (every proposed_allocs rebuild
            # lands here), so it carries the same phase attribution
            with _phases.track("dense_mat"):
                superseded = self._dense_superseded
                for block in blocks:
                    for i in block.node_index_map().get(node_id, ()):
                        if block.ids[i] not in superseded:
                            out.append(block.materialize(i))
        return out

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id) if a.terminal_status() == terminal]

    def allocs_by_job(self, namespace: str, job_id: str, all_allocs: bool) -> List[Allocation]:
        out = [
            self.allocs_table[aid]
            for aid in self._allocs_by_job.get((namespace, job_id), set())
            if aid in self.allocs_table
        ]
        blocks = self._dense_by_job.get((namespace, job_id))
        if blocks:
            out.extend(self._dense_materialize_live(blocks))
        if not all_allocs:
            # Exclude allocs from prior job versions that are terminal? The
            # reference's "all" flag includes allocs of all job create indexes;
            # for scheduling purposes all=True is used.
            pass
        return out

    def allocs_by_job_id(self, job_id: str) -> List[Allocation]:
        """Allocs with this job id across ALL namespaces — the scheduler's
        job anti-affinity matches job_id alone (rank.go:509), so its dense
        encoding must too."""
        out = []
        for (_ns, jid), ids in self._allocs_by_job.items():
            if jid == job_id:
                out.extend(
                    self.allocs_table[a] for a in ids if a in self.allocs_table
                )
        for (_ns, jid), blocks in self._dense_by_job.items():
            if jid == job_id:
                out.extend(self._dense_materialize_live(blocks))
        return out

    def job_has_live_allocs(self, job_id: str) -> bool:
        """Any NON-TERMINAL alloc with this job id in ANY namespace,
        without materializing dense allocs (the encode-cache freshness
        guard; job anti-affinity matches job_id alone — rank.go:509).
        Cost: a key scan over jobs-with-allocs plus O(this job's
        allocs) — never the O(allocs) object materialization that
        ``allocs_by_job_id`` performs."""
        for (_ns, jid), ids in self._allocs_by_job.items():
            if jid == job_id:
                for a in ids:
                    alloc = self.allocs_table.get(a)
                    if alloc is not None and not alloc.terminal_status():
                        return True
        for (_ns, jid), blocks in self._dense_by_job.items():
            if jid == job_id:
                for b in blocks:
                    # a dense slot is non-terminal by construction until
                    # a table alloc supersedes it
                    if len(b.ids) > self._dense_dead.get(b.key(), 0):
                        return True
        return False

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        out = [
            self.allocs_table[aid]
            for aid in self._allocs_by_eval.get(eval_id, set())
            if aid in self.allocs_table
        ]
        blocks = self._dense_by_eval.get(eval_id)
        if blocks:
            out.extend(self._dense_materialize_live(blocks))
        return out

    # ------------------------------------------------------------------
    # deployments
    # ------------------------------------------------------------------

    def upsert_deployment(self, index: int, deployment: Deployment) -> None:
        with self._lock:
            existing = self.deployments_table.get(deployment.id)
            if existing is not None:
                deployment.create_index = existing.create_index
            else:
                deployment.create_index = index
            deployment.modify_index = index
            self.deployments_table[deployment.id] = deployment
            self._deployments_by_job.setdefault(
                (deployment.namespace, deployment.job_id), set()
            ).add(deployment.id)
            self._bump(index)

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self.deployments_table.get(deployment_id)

    def deployments(self) -> List[Deployment]:
        return list(self.deployments_table.values())

    def latest_deployment_by_job_id(self, namespace: str, job_id: str) -> Optional[Deployment]:
        ids = self._deployments_by_job.get((namespace, job_id), set())
        latest = None
        for did in ids:
            d = self.deployments_table.get(did)
            if d is None:
                continue
            if latest is None or d.create_index > latest.create_index:
                latest = d
        return latest

    def delete_deployment(self, index: int, deployment_ids: List[str]) -> None:
        with self._lock:
            for did in deployment_ids:
                d = self.deployments_table.pop(did, None)
                if d is not None:
                    s = self._deployments_by_job.get((d.namespace, d.job_id))
                    if s is not None:
                        s.discard(did)
            self._bump(index)

    # ------------------------------------------------------------------
    # scheduler config
    # ------------------------------------------------------------------

    def upsert_periodic_launch(
        self, index: int, namespace: str, job_id: str, launch_ns: int
    ) -> None:
        with self._lock:
            key = (namespace, job_id)
            self.periodic_launch_table[key] = max(
                self.periodic_launch_table.get(key, 0), launch_ns
            )
            self._bump(index)

    def periodic_launch_by_id(self, namespace: str, job_id: str) -> int:
        """Last recorded launch time ns, 0 if never launched."""
        return self.periodic_launch_table.get((namespace, job_id), 0)

    def delete_periodic_launch(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            self.periodic_launch_table.pop((namespace, job_id), None)
            self._bump(index)

    def scheduler_config(self) -> Tuple[int, Optional[SchedulerConfiguration]]:
        cfg = self.scheduler_config_entry
        return (cfg.modify_index if cfg else 0), cfg

    def scheduler_set_config(self, index: int, config: SchedulerConfiguration) -> None:
        with self._lock:
            config.modify_index = index
            if self.scheduler_config_entry is None:
                config.create_index = index
            else:
                config.create_index = self.scheduler_config_entry.create_index
            self.scheduler_config_entry = config
            self._bump(index)

    def autopilot_config(self):
        cfg = self.autopilot_config_entry
        return (cfg.modify_index if cfg else 0), cfg

    def autopilot_set_config(self, index: int, config) -> None:
        with self._lock:
            config.modify_index = index
            if self.autopilot_config_entry is None:
                config.create_index = index
            else:
                config.create_index = self.autopilot_config_entry.create_index
            self.autopilot_config_entry = config
            self._bump(index)

    # ------------------------------------------------------------------
    # ACL policies / tokens (reference state_store.go UpsertACLPolicies,
    # ACLPolicyByName, UpsertACLTokens, ACLTokenBySecretID, BootstrapACLTokens)
    # ------------------------------------------------------------------

    def upsert_acl_policies(self, index: int, policies) -> None:
        with self._lock:
            for pol in policies:
                existing = self.acl_policies_table.get(pol.name)
                pol = copy.deepcopy(pol)
                pol.modify_index = index
                pol.create_index = existing.create_index if existing else index
                self.acl_policies_table[pol.name] = pol
            self._bump(index)

    def delete_acl_policies(self, index: int, names) -> None:
        with self._lock:
            for name in names:
                self.acl_policies_table.pop(name, None)
            self._bump(index)

    # -- vault accessors (state_store.go UpsertVaultAccessor) -----------

    def upsert_vault_accessors(self, index: int, records) -> None:
        """records: [{"alloc_id", "task", "accessor"}]."""
        with self._lock:
            for rec in records:
                self.vault_accessors_table.setdefault(rec["alloc_id"], []).append(
                    {"task": rec["task"], "accessor": rec["accessor"]}
                )
            self._bump(index)

    def delete_vault_accessors(self, index: int, alloc_ids) -> None:
        with self._lock:
            for alloc_id in alloc_ids:
                self.vault_accessors_table.pop(alloc_id, None)
            self._bump(index)

    def vault_accessors_by_alloc(self, alloc_id: str) -> list:
        with self._lock:
            return list(self.vault_accessors_table.get(alloc_id, []))

    def acl_policy_by_name(self, name: str):
        return self.acl_policies_table.get(name)

    def acl_policies(self):
        return sorted(self.acl_policies_table.values(), key=lambda p: p.name)

    def upsert_acl_tokens(self, index: int, tokens) -> None:
        with self._lock:
            for tok in tokens:
                existing = self.acl_tokens_table.get(tok.accessor_id)
                tok = copy.deepcopy(tok)
                tok.modify_index = index
                tok.create_index = existing.create_index if existing else index
                if existing is not None and existing.secret_id != tok.secret_id:
                    self._tokens_by_secret.pop(existing.secret_id, None)
                self.acl_tokens_table[tok.accessor_id] = tok
                if tok.secret_id:
                    self._tokens_by_secret[tok.secret_id] = tok.accessor_id
            self._bump(index)

    def delete_acl_tokens(self, index: int, accessor_ids) -> None:
        with self._lock:
            for accessor in accessor_ids:
                tok = self.acl_tokens_table.pop(accessor, None)
                if tok is not None:
                    self._tokens_by_secret.pop(tok.secret_id, None)
            self._bump(index)

    def acl_token_by_accessor(self, accessor_id: str):
        return self.acl_tokens_table.get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        accessor = self._tokens_by_secret.get(secret_id)
        return self.acl_tokens_table.get(accessor) if accessor else None

    def acl_tokens(self):
        return sorted(self.acl_tokens_table.values(), key=lambda t: t.accessor_id)

    def bootstrap_acl_token(self, index: int, token) -> None:
        """One-shot bootstrap (reference state_store.go BootstrapACLTokens)."""
        with self._lock:
            if self.acl_bootstrap_index != 0:
                raise ValueError("ACL bootstrap already done")
            self.acl_bootstrap_index = index
        self.upsert_acl_tokens(index, [token])

    # ------------------------------------------------------------------
    # plan results (the alloc commit path — reference state_store.go:227)
    # ------------------------------------------------------------------

    def upsert_plan_results(
        self,
        index: int,
        alloc_updates: List[Allocation],
        allocs_stopped: List[Allocation],
        allocs_preempted: List[Allocation],
        deployment: Optional[Deployment] = None,
        deployment_updates: Optional[List] = None,
        eval_id: str = "",
        preempted_eval_ids: Optional[List[str]] = None,
        timestamp_ns: int = 0,
        dense_placements: Optional[List] = None,
    ) -> None:
        with self._lock:
            # Which updates are *new to their deployment*? Decided against
            # pre-upsert state so in-place updates of already-counted allocs
            # don't inflate placement counters (reference
            # state_store.go updateDeploymentWithAlloc).
            newly_deployed = []
            for alloc in alloc_updates:
                if not alloc.deployment_id:
                    continue
                existing = self.allocs_table.get(alloc.id)
                if existing is None or existing.deployment_id != alloc.deployment_id:
                    newly_deployed.append(alloc)
            if deployment is not None:
                existing = self.deployments_table.get(deployment.id)
                if existing is not None:
                    deployment.create_index = existing.create_index
                else:
                    deployment.create_index = index
                deployment.modify_index = index
                self.deployments_table[deployment.id] = deployment
                self._deployments_by_job.setdefault(
                    (deployment.namespace, deployment.job_id), set()
                ).add(deployment.id)
            for update in deployment_updates or []:
                d = self.deployments_table.get(update.deployment_id)
                if d is not None:
                    d = d.copy()
                    d.status = update.status
                    d.status_description = update.status_description
                    d.modify_index = index
                    self.deployments_table[d.id] = d
            self._upsert_allocs_impl(index, alloc_updates + allocs_stopped + allocs_preempted)
            for block in dense_placements or []:
                self._insert_dense_block(index, block, timestamp_ns)
            by_deployment: Dict[str, List[Allocation]] = {}
            for alloc in newly_deployed:
                by_deployment.setdefault(alloc.deployment_id, []).append(alloc)
            for deployment_id, group in by_deployment.items():
                self._update_deployment_placements(index, deployment_id, group, timestamp_ns)
            self._bump(index)

    def _insert_dense_block(self, index: int, block, timestamp_ns: int) -> None:
        """Commit one dense placement block: O(block) id-map inserts and
        O(touched nodes) mirror/index updates — no per-alloc objects.
        Fresh ids by construction (the engine mints them), so there is no
        existing-version handling."""
        block.stamp(index, timestamp_ns)
        self.capacity_epoch += 1
        self.usage_epoch += 1
        self._dense_blocks.append(block)
        self._index_dense_block_locked(block)
        ask = block.ask_vec
        for node_id, idxs in block.node_index_map().items():
            cnt = len(idxs)
            row = self._node_usage.get(node_id, (0.0, 0.0, 0.0, 0.0))
            self._node_usage[node_id] = (
                row[0] + cnt * ask[0], row[1] + cnt * ask[1],
                row[2] + cnt * ask[2], row[3] + cnt * ask[3],
            )
        if block.deployment_id:
            d = self.deployments_table.get(block.deployment_id)
            if d is not None and d.active():
                d = d.copy()
                ds = d.task_groups.get(block.task_group)
                if ds is not None:
                    ds.placed_allocs += len(block.ids)
                    if ds.progress_deadline_ns > 0 and ds.require_progress_by_ns == 0:
                        ds.require_progress_by_ns = (
                            timestamp_ns + ds.progress_deadline_ns
                        )
                    d.modify_index = index
                    self.deployments_table[d.id] = d

    def _update_deployment_placements(
        self, index: int, deployment_id: str, allocs: List[Allocation], timestamp_ns: int
    ) -> None:
        """Maintain placement counters on one deployment for a batch of newly
        placed allocs (reference state_store.go updateDeploymentWithAlloc).
        One deployment copy per plan, not per alloc: C1M-scale plans place
        many allocs of the same deployment. ``timestamp_ns`` is stamped by
        the plan applier before the raft apply so replicas and log replays
        arm identical progress deadlines."""
        d = self.deployments_table.get(deployment_id)
        if d is None or not d.active():
            return
        d = d.copy()
        changed = False
        for alloc in allocs:
            ds = d.task_groups.get(alloc.task_group)
            if ds is None:
                continue
            changed = True
            ds.placed_allocs += 1
            if alloc.deployment_status is not None and alloc.deployment_status.canary:
                if alloc.id not in ds.placed_canaries:
                    ds.placed_canaries.append(alloc.id)
            if ds.progress_deadline_ns > 0 and ds.require_progress_by_ns == 0:
                ds.require_progress_by_ns = timestamp_ns + ds.progress_deadline_ns
        if changed:
            d.modify_index = index
            self.deployments_table[d.id] = d

    def update_deployment_alloc_health(
        self,
        index: int,
        deployment_id: str,
        healthy_ids: List[str],
        unhealthy_ids: List[str],
        timestamp_ns: int,
    ) -> None:
        """Apply explicit health reports to allocs + deployment counters
        (reference state_store.go UpdateDeploymentAllocHealth)."""
        from ..structs.structs import AllocDeploymentStatus

        with self._lock:
            updates: List[Allocation] = []
            flips: List[Tuple[Optional[bool], Allocation]] = []
            for alloc_id, healthy in [(i, True) for i in healthy_ids] + [
                (i, False) for i in unhealthy_ids
            ]:
                alloc = self.allocs_table.get(alloc_id)
                if alloc is None or alloc.deployment_id != deployment_id:
                    # A report for an alloc of another (e.g. superseded)
                    # deployment must not touch this deployment's counters.
                    continue
                prev = (
                    alloc.deployment_status.healthy
                    if alloc.deployment_status is not None
                    else None
                )
                updated = alloc.copy_skip_job()
                if updated.deployment_status is None:
                    updated.deployment_status = AllocDeploymentStatus()
                updated.deployment_status.healthy = healthy
                updated.deployment_status.timestamp_ns = timestamp_ns
                updates.append(updated)
                flips.append((prev, updated))
            self._upsert_allocs_impl(index, updates)
            self._apply_health_deltas(index, deployment_id, flips)
            self._bump(index)

    def _apply_health_deltas(
        self,
        index: int,
        deployment_id: str,
        flips: List[Tuple[Optional[bool], Allocation]],
    ) -> None:
        """Delta a batch of health flips into one deployment's counters with
        a single deployment copy (reference state_store.go
        updateDeploymentWithAlloc health deltas); a newly healthy alloc also
        extends the group progress deadline."""
        d = self.deployments_table.get(deployment_id)
        if d is None or not d.active():
            return
        d = d.copy()
        changed = False
        for prev_healthy, alloc in flips:
            if alloc.deployment_status is None:
                continue
            healthy = alloc.deployment_status.healthy
            if healthy is None or healthy is prev_healthy:
                continue
            ds = d.task_groups.get(alloc.task_group)
            if ds is None:
                continue
            changed = True
            if healthy:
                ds.healthy_allocs += 1
                if prev_healthy is False:
                    ds.unhealthy_allocs -= 1
                if ds.progress_deadline_ns > 0:
                    ts = alloc.deployment_status.timestamp_ns or 0
                    ds.require_progress_by_ns = max(
                        ds.require_progress_by_ns, ts + ds.progress_deadline_ns
                    )
            else:
                ds.unhealthy_allocs += 1
                if prev_healthy is True:
                    ds.healthy_allocs -= 1
        if changed:
            d.modify_index = index
            self.deployments_table[d.id] = d

    def update_job_stability(
        self, index: int, namespace: str, job_id: str, version: int, stable: bool
    ) -> None:
        """Flag one job version (in place, no version bump) as stable
        (reference state_store.go UpdateJobStability)."""
        with self._lock:
            key = (namespace, job_id)
            versions = self.job_versions.get(key)
            if versions is not None:
                # copy-on-write: snapshots share the stored Job objects
                self.job_versions[key] = [
                    self._with_stability(j, index, stable) if j.version == version else j
                    for j in versions
                ]
            current = self.jobs_table.get(key)
            if current is not None and current.version == version:
                self.jobs_table[key] = self._with_stability(current, index, stable)
            self._bump(index)

    @staticmethod
    def _with_stability(job: Job, index: int, stable: bool) -> Job:
        j = job.copy()
        j.stable = stable
        j.modify_index = index
        return j

    # ------------------------------------------------------------------
    # job status summaries
    # ------------------------------------------------------------------

    def job_summary(self, namespace: str, job_id: str) -> Dict[str, Dict[str, int]]:
        summary: Dict[str, Dict[str, int]] = {}
        for alloc in self.allocs_by_job(namespace, job_id, True):
            tg = summary.setdefault(
                alloc.task_group,
                {"queued": 0, "complete": 0, "failed": 0, "running": 0, "starting": 0, "lost": 0},
            )
            cs = alloc.client_status
            if cs == ALLOC_CLIENT_FAILED:
                tg["failed"] += 1
            elif cs == ALLOC_CLIENT_COMPLETE:
                tg["complete"] += 1
            elif cs == ALLOC_CLIENT_LOST:
                tg["lost"] += 1
            elif cs == "running":
                tg["running"] += 1
            else:
                tg["starting"] += 1
        return summary
