"""Data model for the TPU-native orchestrator (reference nomad/structs/)."""
from .structs import *  # noqa: F401,F403
from .funcs import (  # noqa: F401
    BIN_PACKING_MAX_FIT_SCORE,
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)
from .network import NetworkIndex, parse_port_ranges  # noqa: F401
from .devices import DeviceAccounter  # noqa: F401
from .node_class import (  # noqa: F401
    compute_node_class,
    constraint_target_escapes,
    escaped_constraints,
    is_unique_namespace,
)
