"""ACL data model (reference nomad/structs/structs.go ACLPolicy:~9100,
ACLToken, and nomad/structs/structs.go anonymous/bootstrap token handling)."""

from __future__ import annotations

import secrets
import time
from dataclasses import dataclass, field
from typing import List

from .structs import generate_uuid

ACL_TOKEN_TYPE_CLIENT = "client"
ACL_TOKEN_TYPE_MANAGEMENT = "management"

#: The implicit token used when no secret is presented (structs.go
#: AnonymousACLToken).
ANONYMOUS_ACCESSOR = "anonymous"


@dataclass
class ACLPolicy:
    name: str = ""
    description: str = ""
    rules: str = ""
    create_index: int = 0
    modify_index: int = 0

    def validate(self) -> List[str]:
        errors = []
        if not self.name or len(self.name) > 128:
            errors.append("invalid policy name")
        return errors


@dataclass
class ACLToken:
    accessor_id: str = field(default_factory=generate_uuid)
    secret_id: str = field(default_factory=generate_uuid)
    name: str = ""
    type: str = ACL_TOKEN_TYPE_CLIENT
    policies: List[str] = field(default_factory=list)
    global_: bool = False
    create_time_ns: int = field(default_factory=lambda: time.time_ns())
    create_index: int = 0
    modify_index: int = 0

    def is_management(self) -> bool:
        return self.type == ACL_TOKEN_TYPE_MANAGEMENT

    def validate(self) -> List[str]:
        errors = []
        if self.type not in (ACL_TOKEN_TYPE_CLIENT, ACL_TOKEN_TYPE_MANAGEMENT):
            errors.append(f"invalid token type {self.type!r}")
        if self.type == ACL_TOKEN_TYPE_CLIENT and not self.policies:
            errors.append("client token missing policies")
        if self.type == ACL_TOKEN_TYPE_MANAGEMENT and self.policies:
            errors.append("management token cannot be assigned policies")
        return errors

    def public_stub(self) -> "ACLToken":
        """Copy without the secret (listing endpoints never leak secrets)."""
        return ACLToken(
            accessor_id=self.accessor_id,
            secret_id="",
            name=self.name,
            type=self.type,
            policies=list(self.policies),
            global_=self.global_,
            create_time_ns=self.create_time_ns,
            create_index=self.create_index,
            modify_index=self.modify_index,
        )


def anonymous_token() -> ACLToken:
    return ACLToken(
        accessor_id=ANONYMOUS_ACCESSOR,
        secret_id="",
        name="Anonymous Token",
        type=ACL_TOKEN_TYPE_CLIENT,
        policies=["anonymous"],
    )


def bootstrap_token() -> ACLToken:
    return ACLToken(
        name="Bootstrap Token",
        type=ACL_TOKEN_TYPE_MANAGEMENT,
        global_=True,
        secret_id=secrets.token_hex(16),
    )
