"""Device instance accounting (reference ``nomad/structs/devices.go``)."""
from __future__ import annotations

from typing import Dict, List, Optional

from .structs import (
    AllocatedDeviceResource,
    Allocation,
    DeviceIdTuple,
    Node,
    NodeDeviceResource,
)


class DeviceAccounterInstance:
    def __init__(self, device: NodeDeviceResource) -> None:
        self.device = device
        # instance id -> use count; 0 means free
        self.instances: Dict[str, int] = {
            inst.id: 0 for inst in device.instances if inst.healthy
        }

    def free_count(self) -> int:
        return sum(1 for v in self.instances.values() if v == 0)


class DeviceAccounter:
    """Tracks device usage on a node to detect oversubscription."""

    def __init__(self, node: Node) -> None:
        self.devices: Dict[DeviceIdTuple, DeviceAccounterInstance] = {}
        for dev in node.node_resources.devices:
            self.devices[dev.id()] = DeviceAccounterInstance(dev)

    def add_allocs(self, allocs: List[Allocation]) -> bool:
        """Mark devices used by allocs; True if any instance is double-used."""
        collision = False
        for a in allocs:
            if a.terminal_status():
                continue
            if a.allocated_resources is None:
                continue
            for tr in a.allocated_resources.tasks.values():
                for device in tr.devices:
                    dev_inst = self.devices.get(device.id())
                    if dev_inst is None:
                        continue
                    for instance_id in device.device_ids:
                        if instance_id in dev_inst.instances:
                            if dev_inst.instances[instance_id] != 0:
                                collision = True
                            dev_inst.instances[instance_id] += 1
        return collision

    def add_reserved(self, res: AllocatedDeviceResource) -> bool:
        collision = False
        dev_inst = self.devices.get(res.id())
        if dev_inst is None:
            return False
        for instance_id in res.device_ids:
            if instance_id not in dev_inst.instances:
                continue
            if dev_inst.instances[instance_id] != 0:
                collision = True
            dev_inst.instances[instance_id] += 1
        return collision
