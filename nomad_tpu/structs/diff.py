"""Job diffs for ``nomad plan`` output.

Fills the role of the reference's ``nomad/structs/diff.go`` (Job.Diff):
a structural old-vs-new comparison rendered as nested {Type, Name, Old,
New} records — Type ∈ {None, Added, Deleted, Edited}. Collections of
named objects (task groups, tasks) are matched by name; everything else
diffs field-by-field off the dataclass definition.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

# Fields that are server bookkeeping, not part of the user's specification.
_IGNORED_FIELDS = {
    "create_index",
    "modify_index",
    "job_modify_index",
    "alloc_modify_index",
    "version",
    "status",
    "status_description",
    "stable",
    "submit_time",
}

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"


def _camel(name: str) -> str:
    from ..agent.jsonapi import camel

    return camel(name)


def _render(value: Any) -> str:
    if value is None:
        return ""
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def _field_diffs(old: Any, new: Any, fields) -> List[Dict]:
    out = []
    for f in fields:
        if f.name in _IGNORED_FIELDS:
            continue
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None
        if dataclasses.is_dataclass(ov) or dataclasses.is_dataclass(nv):
            continue  # nested objects handled by object diffs
        if isinstance(ov, (list, dict)) or isinstance(nv, (list, dict)):
            if ov != nv:
                out.append(
                    {
                        "Type": DIFF_EDITED,
                        "Name": _camel(f.name),
                        "Old": _render(ov),
                        "New": _render(nv),
                    }
                )
            continue
        if ov != nv:
            _empty = (None, "", 0, False)
            if old is None or (ov in _empty and nv not in _empty):
                kind = DIFF_ADDED
            elif new is None or (nv in _empty and ov not in _empty):
                kind = DIFF_DELETED
            else:
                kind = DIFF_EDITED
            out.append(
                {
                    "Type": kind,
                    "Name": _camel(f.name),
                    "Old": _render(ov),
                    "New": _render(nv),
                }
            )
    return out


def _object_diff(name: str, old: Any, new: Any) -> Optional[Dict]:
    """Diff two optional dataclass values (update block, periodic, ...)."""
    if old is None and new is None:
        return None
    cls = type(new if new is not None else old)
    fields = dataclasses.fields(cls)
    fdiffs = _field_diffs(old, new, fields)
    if not fdiffs and old is not None and new is not None:
        return None
    kind = DIFF_ADDED if old is None else (DIFF_DELETED if new is None else DIFF_EDITED)
    return {"Type": kind, "Name": name, "Fields": fdiffs}


def _task_diff(old, new) -> Optional[Dict]:
    name = (new or old).name
    fdiffs = _field_diffs(old, new, dataclasses.fields(type(new or old)))
    if old is None:
        return {"Type": DIFF_ADDED, "Name": name, "Fields": fdiffs}
    if new is None:
        return {"Type": DIFF_DELETED, "Name": name, "Fields": fdiffs}
    if not fdiffs:
        return None
    return {"Type": DIFF_EDITED, "Name": name, "Fields": fdiffs}


def _tg_diff(old, new) -> Optional[Dict]:
    name = (new or old).name
    fdiffs = _field_diffs(old, new, dataclasses.fields(type(new or old)))
    task_diffs = _named_list_diffs(
        old.tasks if old else [], new.tasks if new else [], _task_diff
    )
    objs = []
    for attr in ("restart_policy", "reschedule_policy", "update", "migrate_strategy",
                 "ephemeral_disk"):
        d = _object_diff(
            _camel(attr),
            getattr(old, attr, None) if old else None,
            getattr(new, attr, None) if new else None,
        )
        if d is not None:
            objs.append(d)
    if old is None:
        kind = DIFF_ADDED
    elif new is None:
        kind = DIFF_DELETED
    elif fdiffs or task_diffs or objs:
        kind = DIFF_EDITED
    else:
        return None
    return {
        "Type": kind,
        "Name": name,
        "Fields": fdiffs,
        "Objects": objs,
        "Tasks": task_diffs,
    }


def _named_list_diffs(olds: List, news: List, differ) -> List[Dict]:
    by_name_old = {o.name: o for o in olds}
    by_name_new = {n.name: n for n in news}
    out = []
    for name in sorted(set(by_name_old) | set(by_name_new)):
        d = differ(by_name_old.get(name), by_name_new.get(name))
        if d is not None:
            out.append(d)
    return out


def job_diff(old, new) -> Dict:
    """Diff two Jobs; either side may be None (register / stop)."""
    if old is None and new is None:
        return {"Type": DIFF_NONE, "ID": "", "Fields": [], "Objects": [],
                "TaskGroups": []}
    job = new if new is not None else old
    fdiffs = _field_diffs(old, new, dataclasses.fields(type(job)))
    tg_diffs = _named_list_diffs(
        old.task_groups if old else [], new.task_groups if new else [], _tg_diff
    )
    objs = []
    for attr in ("update", "periodic", "parameterized"):
        d = _object_diff(
            _camel(attr),
            getattr(old, attr, None) if old else None,
            getattr(new, attr, None) if new else None,
        )
        if d is not None:
            objs.append(d)
    if old is None:
        kind = DIFF_ADDED
    elif new is None:
        kind = DIFF_DELETED
    elif fdiffs or tg_diffs or objs:
        kind = DIFF_EDITED
    else:
        kind = DIFF_NONE
    return {
        "Type": kind,
        "ID": job.id,
        "Fields": fdiffs,
        "Objects": objs,
        "TaskGroups": tg_diffs,
    }
