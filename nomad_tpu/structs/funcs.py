"""Pure scheduling fit functions.

Semantics match the reference ``nomad/structs/funcs.go`` (AllocsFit :102,
ScoreFit :154, FilterTerminalAllocs :74, RemoveAllocs :51).  These host-side
scalar versions are the oracle for the vectorized TPU implementations in
``nomad_tpu/tpu/engine.py``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .network import NetworkIndex
from .devices import DeviceAccounter
from .structs import Allocation, ComparableResources, Node

# ScoreFit's normalization ceiling: a perfectly empty node scores 18
# (20 - 10^1 - 10^1 ... inverted); see reference funcs.go:154-188.
BIN_PACKING_MAX_FIT_SCORE = 18.0


def alloc_usage_vec(alloc: Allocation) -> tuple:
    """(cpu, mem, disk, mbits) consumed by one alloc; memoized on the
    (immutable — stores insert copies) alloc object. Shared by the state
    store's incremental per-node usage mirror and the TPU encode layer."""
    u = alloc.__dict__.get("_usage_vec")
    if u is None:
        cr = alloc.comparable_resources()
        mb = 0
        if alloc.allocated_resources is not None:
            for net in alloc.allocated_resources.shared.networks:
                mb += net.mbits
            for tr in alloc.allocated_resources.tasks.values():
                for net in tr.networks:
                    mb += net.mbits
        u = (
            float(cr.flattened.cpu_shares), float(cr.flattened.memory_mb),
            float(cr.shared.disk_mb), float(mb),
        )
        alloc.__dict__["_usage_vec"] = u
    return u


def node_capacity_vecs(node: Node) -> Tuple[tuple, tuple]:
    """((cpu, mem, disk, mbits) totals, same-shape reserved) for one node
    — the ONE definition of the 4-dim capacity model shared by the encode
    layer's fleet arrays and the plan applier's dense re-check, so the
    two can never silently diverge.

    Memoized on the node object: stored nodes are immutable (every write
    inserts a copy), and the plan applier's dense re-check calls this per
    touched node per plan — C1M commit rates make the rebuild the
    dominant applier cost otherwise."""
    cached = node.__dict__.get("_cap_vecs")
    if cached is not None:
        return cached
    nr = node.node_resources
    totals = (
        float(nr.cpu_shares), float(nr.memory_mb), float(nr.disk_mb),
        float(sum(net.mbits for net in nr.networks)),
    )
    rr = node.reserved_resources
    reserved = (
        (float(rr.cpu_shares), float(rr.memory_mb), float(rr.disk_mb), 0.0)
        if rr is not None else (0.0, 0.0, 0.0, 0.0)
    )
    node.__dict__["_cap_vecs"] = (totals, reserved)
    return totals, reserved


def remove_allocs(allocs: List[Allocation], remove: List[Allocation]) -> List[Allocation]:
    """Remove by alloc ID (order NOT preserved beyond filtering)."""
    remove_set = {a.id for a in remove}
    return [a for a in allocs if a.id not in remove_set]


def filter_terminal_allocs(
    allocs: List[Allocation],
) -> Tuple[List[Allocation], Dict[str, Allocation]]:
    """Split off terminal allocs, keeping the latest terminal alloc per name."""
    terminal: Dict[str, Allocation] = {}
    live: List[Allocation] = []
    for a in allocs:
        if a.terminal_status():
            prev = terminal.get(a.name)
            if prev is None or prev.create_index < a.create_index:
                terminal[a.name] = a
        else:
            live.append(a)
    return live, terminal


def allocs_fit(
    node: Node,
    allocs: List[Allocation],
    net_idx: Optional[NetworkIndex] = None,
    check_devices: bool = False,
) -> Tuple[bool, str, ComparableResources]:
    """Check whether a set of allocations fits on a node.

    Returns (fit, exhausted_dimension, used). Mirrors reference funcs.go:102.
    """
    used = ComparableResources()

    reserved = node.comparable_reserved_resources()
    if reserved is not None:
        used.add(reserved)

    for alloc in allocs:
        if alloc.terminal_status():
            continue
        used.add(alloc.comparable_resources())

    superset, dimension = node.comparable_resources().superset(used)
    if not superset:
        return False, dimension, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices:
        accounter = DeviceAccounter(node)
        if accounter.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def score_fit(node: Node, util: ComparableResources) -> float:
    """Google BestFit-v3 scoring (reference funcs.go:154).

    20 - (10^freePctCpu + 10^freePctMem); clamped to [0, 18].
    """
    reserved = node.comparable_reserved_resources()
    res = node.comparable_resources()

    node_cpu = float(res.flattened.cpu_shares)
    node_mem = float(res.flattened.memory_mb)
    if reserved is not None:
        node_cpu -= float(reserved.flattened.cpu_shares)
        node_mem -= float(reserved.flattened.memory_mb)

    free_pct_cpu = 1.0 - (float(util.flattened.cpu_shares) / node_cpu)
    free_pct_ram = 1.0 - (float(util.flattened.memory_mb) / node_mem)

    total = 10.0**free_pct_cpu + 10.0**free_pct_ram
    score = 20.0 - total

    if score > 18.0:
        score = 18.0
    elif score < 0.0:
        score = 0.0
    return score
