"""Port/bandwidth accounting for node networks.

Same semantics as the reference ``nomad/structs/network.go`` (NetworkIndex
:43, AssignNetwork, Overcommitted, AddReserved), but implemented with Python
``set``s of used ports instead of pooled 8KB bitmaps, and with a
deterministic-mode port picker so the TPU parity harness can compare plans.
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

from .structs import (
    MAX_DYNAMIC_PORT,
    MAX_VALID_PORT,
    MIN_DYNAMIC_PORT,
    Allocation,
    NetworkResource,
    Node,
    Port,
)

MAX_RAND_PORT_ATTEMPTS = 20


def parse_port_ranges(spec: str) -> List[int]:
    """Parse "80,100-200,205" into a sorted port list."""
    ports: Set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, hi_s = part.split("-", 1)
            lo, hi = int(lo_s), int(hi_s)
            if lo > hi:
                raise ValueError(f"invalid port range {part}")
            ports.update(range(lo, hi + 1))
        else:
            ports.add(int(part))
    return sorted(ports)


class NetworkIndex:
    """Tracks available and used network resources on one node."""

    def __init__(self, deterministic: bool = False) -> None:
        self.avail_networks: List[NetworkResource] = []
        self.avail_bandwidth: Dict[str, int] = {}
        self.used_ports: Dict[str, Set[int]] = {}
        self.used_bandwidth: Dict[str, int] = {}
        # Deterministic mode picks the lowest free dynamic ports, for parity
        # testing; the reference always randomizes (network.go stochastic pick).
        self.deterministic = deterministic

    def release(self) -> None:  # compat no-op; no pooled bitmaps here
        pass

    def fork(self) -> "NetworkIndex":
        """Cheap copy for speculative mutation: shares the node's
        avail_networks/avail_bandwidth (only set_node writes those, and
        forks never call it), copies the used-port sets and bandwidth
        tallies so add_reserved on the fork never bleeds into the base."""
        c = NetworkIndex(deterministic=self.deterministic)
        c.avail_networks = self.avail_networks
        c.avail_bandwidth = self.avail_bandwidth
        c.used_ports = {ip: set(ports) for ip, ports in self.used_ports.items()}
        c.used_bandwidth = dict(self.used_bandwidth)
        return c

    def overcommitted(self) -> bool:
        for device, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(device, 0):
                return True
        return False

    def set_node(self, node: Node) -> bool:
        """Set up available networks; returns True on collision."""
        collide = False
        for n in node.node_resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
        if node.reserved_resources is not None and node.reserved_resources.reserved_host_ports:
            if self.add_reserved_port_range(node.reserved_resources.reserved_host_ports):
                collide = True
        return collide

    def add_allocs(self, allocs: List[Allocation]) -> bool:
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.allocated_resources is None:
                continue
            for network in alloc.allocated_resources.shared.networks:
                if self.add_reserved(network):
                    collide = True
            for task in alloc.allocated_resources.tasks.values():
                if not task.networks:
                    continue
                if self.add_reserved(task.networks[0]):
                    collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        collide = False
        used = self.used_ports.setdefault(n.ip, set())
        for ports in (n.reserved_ports, n.dynamic_ports):
            for port in ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    return True
                if port.value in used:
                    collide = True
                else:
                    used.add(port.value)
        self.used_bandwidth[n.device] = self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide

    def add_reserved_port_range(self, ports: str) -> bool:
        try:
            res_ports = parse_port_ranges(ports)
        except ValueError:
            return False
        collide = False
        for n in self.avail_networks:
            self.used_ports.setdefault(n.ip, set())
        for used in self.used_ports.values():
            for port in res_ports:
                if port < 0 or port >= MAX_VALID_PORT:
                    return True
                if port in used:
                    collide = True
                else:
                    used.add(port)
        return collide

    def assign_network(self, ask: NetworkResource) -> Tuple[Optional[NetworkResource], str]:
        """Assign an offer for the ask; returns (offer|None, error_reason)."""
        err = "no networks available"
        for n in self.avail_networks:
            ip = n.ip or (n.cidr.split("/")[0] if n.cidr else "")
            if not ip:
                continue

            avail_bw = self.avail_bandwidth.get(n.device, 0)
            used_bw = self.used_bandwidth.get(n.device, 0)
            if used_bw + ask.mbits > avail_bw:
                err = "bandwidth exceeded"
                continue

            used = self.used_ports.get(ip, set())

            reserved_ok = True
            for port in ask.reserved_ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    err = f"invalid port {port.value} (out of range)"
                    reserved_ok = False
                    break
                if port.value in used:
                    err = "reserved port collision"
                    reserved_ok = False
                    break
            if not reserved_ok:
                continue

            dyn_ports = self._pick_dynamic_ports(used, ask)
            if dyn_ports is None:
                err = "dynamic port selection failed"
                continue

            offer = NetworkResource(
                mode=ask.mode,
                device=n.device,
                ip=ip,
                mbits=ask.mbits,
                reserved_ports=[Port(p.label, p.value, p.to) for p in ask.reserved_ports],
                dynamic_ports=[
                    Port(p.label, v, v if p.to == -1 else p.to)
                    for p, v in zip(ask.dynamic_ports, dyn_ports)
                ],
            )
            return offer, ""
        return None, err

    def _pick_dynamic_ports(self, used: Set[int], ask: NetworkResource) -> Optional[List[int]]:
        needed = len(ask.dynamic_ports)
        if needed == 0:
            return []
        blocked = set(used)
        blocked.update(p.value for p in ask.reserved_ports)

        if self.deterministic:
            out: List[int] = []
            for port in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT):
                if port not in blocked:
                    out.append(port)
                    blocked.add(port)
                    if len(out) == needed:
                        return out
            return None

        # Stochastic pick with precise fallback (reference network.go:318/:281)
        picked: List[int] = []
        for _ in range(needed):
            for _attempt in range(MAX_RAND_PORT_ATTEMPTS):
                cand = random.randint(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT - 1)
                if cand not in blocked:
                    picked.append(cand)
                    blocked.add(cand)
                    break
            else:
                break
        if len(picked) == needed:
            return picked

        available = [p for p in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT) if p not in blocked]
        remaining = needed - len(picked)
        if len(available) < remaining:
            return None
        picked.extend(random.sample(available, remaining))
        return picked
