"""Computed node classes (reference ``nomad/structs/node_class.go``).

A computed class is a stable hash over the *non-unique* identifying fields of
a node: datacenter, node class, attributes, meta, and device signatures.
Nodes sharing a computed class are interchangeable for constraint
feasibility, which collapses O(nodes) checks to O(classes) — and, in the TPU
engine, lets mask tensors be computed per class and gathered per node.
"""
from __future__ import annotations

import hashlib
import json
from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from .structs import Constraint, Node

NODE_UNIQUE_NAMESPACE = "unique."


def is_unique_namespace(key: str) -> bool:
    return key.startswith(NODE_UNIQUE_NAMESPACE)


def compute_node_class(node: "Node") -> str:
    """Stable content hash of the node's class-relevant fields."""
    devices = sorted(
        (
            d.vendor,
            d.type,
            d.name,
            tuple(sorted((k, str(v)) for k, v in d.attributes.items() if not is_unique_namespace(k))),
        )
        for d in node.node_resources.devices
    )
    payload = {
        "datacenter": node.datacenter,
        "node_class": node.node_class,
        "attributes": {k: v for k, v in sorted(node.attributes.items()) if not is_unique_namespace(k)},
        "meta": {k: v for k, v in sorted(node.meta.items()) if not is_unique_namespace(k)},
        "devices": devices,
    }
    digest = hashlib.blake2b(
        json.dumps(payload, sort_keys=True, default=str).encode(), digest_size=8
    ).hexdigest()
    return f"v1:{digest}"


def constraint_target_escapes(target: str) -> bool:
    """Whether a constraint target defeats class-level memoization."""
    return (
        target.startswith("${node.unique.")
        or target.startswith("${attr.unique.")
        or target.startswith("${meta.unique.")
    )


def escaped_constraints(constraints: List["Constraint"]) -> List["Constraint"]:
    """Constraints whose targets escape computed node classes."""
    return [
        c
        for c in constraints
        if constraint_target_escapes(c.ltarget) or constraint_target_escapes(c.rtarget)
    ]
