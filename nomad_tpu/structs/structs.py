"""Core data model for the TPU-native orchestrator.

This is a fresh design with the same semantics as the reference's
``nomad/structs/structs.go`` data model (Node structs.go:1508, Job :3285,
TaskGroup :4687, Task :5263, Allocation :7466, Evaluation :8352, Plan :8645).
Unlike the reference, resources are modelled with a single flattened
``ComparableResources`` representation from the start (the reference carries
legacy 0.8-era shapes alongside; we only implement the 0.9+ semantics), and
every struct is designed so the scheduler can *densify* it into device tensors
(see nomad_tpu/tpu/encode.py).
"""
from __future__ import annotations

import time as _time
import uuid as _uuid
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Constants (reference: nomad/structs/structs.go)
# ---------------------------------------------------------------------------

JOB_TYPE_CORE = "_core"
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

NODE_SCHED_ELIGIBLE = "eligible"
NODE_SCHED_INELIGIBLE = "ineligible"

ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"

EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC_JOB = "periodic-job"
EVAL_TRIGGER_NODE_DRAIN = "node-drain"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_ALLOC_STOP = "alloc-stop"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
EVAL_TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
EVAL_TRIGGER_MAX_PLANS = "max-plan-attempts"
EVAL_TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
EVAL_TRIGGER_QUEUED_ALLOCS = "queued-allocs"
EVAL_TRIGGER_PREEMPTION = "preemption"

CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_FORCE_GC = "force-gc"

# Constraint operands (reference structs.go:6619-6631)
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_ATTRIBUTE_IS_SET = "is_set"
CONSTRAINT_ATTRIBUTE_IS_NOT_SET = "is_not_set"

DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"

DEPLOYMENT_ACTIVE_STATUSES = (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED)

# Dynamic port range (reference structs/network.go:11-15)
MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
MAX_VALID_PORT = 65536


def generate_uuid() -> str:
    return str(_uuid.uuid4())


def generate_uuids(n: int) -> List[str]:
    """Batch-mint n v4-format UUID strings from one entropy read — the
    dense placement path mints one id per placement, and per-call
    ``uuid.uuid4()`` object construction is measurable at that volume."""
    import os as _os

    raw = _os.urandom(16 * n).hex()
    out = []
    for k in range(n):
        h = raw[32 * k : 32 * (k + 1)]
        # stamp version (4) and variant (10xx) nibbles like uuid4
        out.append(
            f"{h[0:8]}-{h[8:12]}-4{h[13:16]}-"
            f"{'89ab'[int(h[16], 16) & 3]}{h[17:20]}-{h[20:32]}"
        )
    return out


def now_ns() -> int:
    return _time.time_ns()


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------


@dataclass
class Port:
    label: str = ""
    value: int = 0
    to: int = 0


@dataclass
class NetworkResource:
    """A network ask or offer (reference structs.go NetworkResource)."""

    mode: str = ""
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            mode=self.mode,
            device=self.device,
            cidr=self.cidr,
            ip=self.ip,
            mbits=self.mbits,
            reserved_ports=[replace(p) for p in self.reserved_ports],
            dynamic_ports=[replace(p) for p in self.dynamic_ports],
        )


@dataclass
class RequestedDevice:
    """A device ask on a task (reference structs.go RequestedDevice).

    ``name`` may be "<vendor>/<type>/<name>", "<type>/<name>" or "<type>".
    """

    name: str = ""
    count: int = 1
    constraints: List["Constraint"] = field(default_factory=list)
    affinities: List["Affinity"] = field(default_factory=list)

    def id(self) -> "DeviceIdTuple":
        parts = self.name.split("/")
        if len(parts) >= 3:
            return DeviceIdTuple(parts[0], parts[1], "/".join(parts[2:]))
        if len(parts) == 2:
            return DeviceIdTuple("", parts[0], parts[1])
        return DeviceIdTuple("", self.name, "")


@dataclass(frozen=True)
class DeviceIdTuple:
    vendor: str = ""
    type: str = ""
    name: str = ""

    def matches(self, ask: "DeviceIdTuple") -> bool:
        """Whether this concrete device group satisfies the (possibly
        partially-specified) ask id (reference structs/devices semantics)."""
        if ask.name and ask.name != self.name:
            return False
        if ask.type and ask.type != self.type:
            return False
        if ask.vendor and ask.vendor != self.vendor:
            return False
        return True


@dataclass
class Resources:
    """Per-task resource ask (reference structs.go Resources)."""

    cpu: int = 0  # MHz
    memory_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[RequestedDevice] = field(default_factory=list)


@dataclass
class AllocatedDeviceResource:
    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: List[str] = field(default_factory=list)

    def id(self) -> DeviceIdTuple:
        return DeviceIdTuple(self.vendor, self.type, self.name)

    def copy(self) -> "AllocatedDeviceResource":
        return AllocatedDeviceResource(
            self.vendor, self.type, self.name, list(self.device_ids)
        )


@dataclass
class AllocatedTaskResources:
    cpu_shares: int = 0
    memory_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[AllocatedDeviceResource] = field(default_factory=list)

    def copy(self) -> "AllocatedTaskResources":
        return AllocatedTaskResources(
            self.cpu_shares, self.memory_mb,
            [n.copy() for n in self.networks],
            [d.copy() for d in self.devices],
        )

    def add(self, other: "AllocatedTaskResources") -> None:
        self.cpu_shares += other.cpu_shares
        self.memory_mb += other.memory_mb

    def add_networks(self, networks: List[NetworkResource]) -> None:
        """Merge networks BY DEVICE (reference structs.go:2981
        AllocatedTaskResources.Add + Networks.NetIndex): an alloc with a
        task net and a group net on the same NIC flattens to ONE entry
        whose mbits/ports accumulate — preemption reads Networks[0]."""
        for n in networks:
            for mine in self.networks:
                if mine.device == n.device:
                    mine.mbits += n.mbits
                    mine.reserved_ports = list(mine.reserved_ports) + list(n.reserved_ports)
                    mine.dynamic_ports = list(mine.dynamic_ports) + list(n.dynamic_ports)
                    break
            else:
                self.networks.append(n.copy())

    def subtract(self, other: "AllocatedTaskResources") -> None:
        self.cpu_shares -= other.cpu_shares
        self.memory_mb -= other.memory_mb


@dataclass
class AllocatedSharedResources:
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    def copy(self) -> "AllocatedSharedResources":
        return AllocatedSharedResources(
            self.disk_mb, [n.copy() for n in self.networks]
        )


@dataclass
class AllocatedResources:
    tasks: Dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)

    def copy(self) -> "AllocatedResources":
        return AllocatedResources(
            {k: v.copy() for k, v in self.tasks.items()}, self.shared.copy()
        )

    def comparable(self) -> "ComparableResources":
        c = ComparableResources()
        for tr in self.tasks.values():
            c.flattened.add(tr)
            c.flattened.add_networks(tr.networks)
        c.shared.disk_mb = self.shared.disk_mb
        c.flattened.add_networks(self.shared.networks)
        return c


@dataclass
class ComparableResources:
    """Flattened task-group resources (reference structs.go:3192)."""

    flattened: AllocatedTaskResources = field(default_factory=AllocatedTaskResources)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)

    def add(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.flattened.add(other.flattened)
        self.shared.disk_mb += other.shared.disk_mb

    def subtract(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.flattened.subtract(other.flattened)
        self.shared.disk_mb -= other.shared.disk_mb

    def superset(self, other: "ComparableResources") -> Tuple[bool, str]:
        """Reference structs.go:3227 — ignores networks."""
        if self.flattened.cpu_shares < other.flattened.cpu_shares:
            return False, "cpu"
        if self.flattened.memory_mb < other.flattened.memory_mb:
            return False, "memory"
        if self.shared.disk_mb < other.shared.disk_mb:
            return False, "disk"
        return True, ""

    def copy(self) -> "ComparableResources":
        c = ComparableResources()
        c.add(self)
        return c


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class NodeDeviceInstance:
    id: str = ""
    healthy: bool = True
    locality: str = ""


@dataclass
class NodeDeviceResource:
    vendor: str = ""
    type: str = ""
    name: str = ""
    instances: List[NodeDeviceInstance] = field(default_factory=list)
    attributes: Dict[str, Any] = field(default_factory=dict)

    def id(self) -> DeviceIdTuple:
        return DeviceIdTuple(self.vendor, self.type, self.name)


@dataclass
class NodeResources:
    cpu_shares: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[NodeDeviceResource] = field(default_factory=list)

    def comparable(self) -> ComparableResources:
        c = ComparableResources()
        c.flattened.cpu_shares = self.cpu_shares
        c.flattened.memory_mb = self.memory_mb
        c.shared.disk_mb = self.disk_mb
        c.flattened.networks = list(self.networks)
        return c


@dataclass
class NodeReservedResources:
    cpu_shares: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_host_ports: str = ""

    def comparable(self) -> ComparableResources:
        c = ComparableResources()
        c.flattened.cpu_shares = self.cpu_shares
        c.flattened.memory_mb = self.memory_mb
        c.shared.disk_mb = self.disk_mb
        return c


@dataclass
class DriverInfo:
    name: str = ""
    detected: bool = False
    healthy: bool = False
    health_description: str = ""


@dataclass
class HostVolume:
    name: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class DrainStrategy:
    """How a node drain proceeds (reference structs.go DrainStrategy /
    DrainSpec): ``deadline_ns`` is the grace duration (-1 forces an
    immediate drain, 0 means no deadline); ``force_deadline_ns`` is the
    wall-clock instant the drainer force-migrates everything, stamped by
    the endpoint before the raft apply so replicas agree."""

    deadline_ns: int = 60 * 60 * 10**9
    ignore_system_jobs: bool = False
    force_deadline_ns: int = 0

    def deadline_passed(self, now_ns: int) -> bool:
        if self.deadline_ns < 0:
            return True
        return self.force_deadline_ns > 0 and now_ns >= self.force_deadline_ns


@dataclass
class Node:
    """A client node (reference structs.go:1508)."""

    id: str = field(default_factory=generate_uuid)
    # shared secret minted by the client at first boot; authenticates
    # node-scoped RPCs like Node.DeriveVaultToken (structs.go Node.SecretID)
    # — scrubbed from read endpoints, never returned to other callers
    secret_id: str = field(default_factory=generate_uuid)
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: Optional[NodeReservedResources] = None
    drivers: Dict[str, DriverInfo] = field(default_factory=dict)
    host_volumes: Dict[str, HostVolume] = field(default_factory=dict)
    status: str = NODE_STATUS_READY
    status_description: str = ""
    scheduling_eligibility: str = NODE_SCHED_ELIGIBLE
    drain: bool = False
    drain_strategy: Optional[DrainStrategy] = None
    computed_class: str = ""
    http_addr: str = ""
    create_index: int = 0
    modify_index: int = 0

    def comparable_resources(self) -> ComparableResources:
        return self.node_resources.comparable()

    def comparable_reserved_resources(self) -> Optional[ComparableResources]:
        if self.reserved_resources is None:
            return None
        return self.reserved_resources.comparable()

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def ready(self) -> bool:
        return (
            self.status == NODE_STATUS_READY
            and not self.drain
            and self.scheduling_eligibility == NODE_SCHED_ELIGIBLE
        )

    def compute_class(self) -> None:
        from .node_class import compute_node_class

        self.computed_class = compute_node_class(self)

    def copy(self) -> "Node":
        import copy as _copy

        c = _copy.deepcopy(self)
        # derived caches (funcs.node_capacity_vecs) must not survive into
        # a copy whose resources the caller may go on to mutate
        c.__dict__.pop("_cap_vecs", None)
        return c

    def without_secret(self) -> "Node":
        """Shallow copy with secret_id cleared — what read endpoints
        return (node_endpoint.go GetNode clears SecretID before replying).
        Shallow is safe: stored nodes are treated as immutable."""
        if not self.secret_id:
            return self
        import dataclasses as _dc

        return _dc.replace(self, secret_id="")


# ---------------------------------------------------------------------------
# Job spec
# ---------------------------------------------------------------------------


@dataclass
class Constraint:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass
class Affinity:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="
    weight: int = 0  # [-100, 100]


@dataclass
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass
class Spread:
    attribute: str = ""
    weight: int = 0
    spread_target: List[SpreadTarget] = field(default_factory=list)


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 150
    migrate: bool = False


@dataclass
class ReschedulePolicy:
    attempts: int = 0
    interval_ns: int = 0
    delay_ns: int = 0
    delay_function: str = "constant"  # constant | exponential | fibonacci
    max_delay_ns: int = 0
    unlimited: bool = False


@dataclass
class RestartPolicy:
    attempts: int = 2
    interval_ns: int = 30 * 60 * 10**9
    delay_ns: int = 15 * 10**9
    mode: str = "fail"


@dataclass
class UpdateStrategy:
    """Task-group update strategy (reference structs.go UpdateStrategy)."""

    stagger_ns: int = 30 * 10**9
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_ns: int = 10 * 10**9
    healthy_deadline_ns: int = 5 * 60 * 10**9
    progress_deadline_ns: int = 10 * 60 * 10**9
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def rolling(self) -> bool:
        return self.max_parallel > 0


@dataclass
class MigrateStrategy:
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_ns: int = 10 * 10**9
    healthy_deadline_ns: int = 5 * 60 * 10**9


@dataclass
class VolumeRequest:
    name: str = ""
    type: str = "host"
    source: str = ""
    read_only: bool = False


@dataclass
class VolumeMount:
    """A task's mount of a group volume (reference structs.go VolumeMount)."""

    volume: str = ""
    destination: str = ""
    read_only: bool = False


VOLUME_TYPE_HOST = "host"


@dataclass
class Service:
    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    # check stanzas as plain dicts: {"name", "type", "ttl", "http",
    # "interval", ...} (reference structs.go ServiceCheck)
    checks: List[Dict[str, Any]] = field(default_factory=list)
    # Consul Connect stanza as a plain dict (reference structs.go
    # ConsulConnect): {"sidecar_service": {"port": ..., "proxy": {...}},
    # "sidecar_task": {"driver": ..., "config": {...}, ...}}
    connect: Optional[Dict[str, Any]] = None

    def has_sidecar(self) -> bool:
        return bool(self.connect and "sidecar_service" in self.connect)


#: Connect sidecar naming (reference structs.go ConnectProxyPrefix) —
#: shared by the server's injection hook and the client's Consul
#: registration (proxy port label / task kind).
CONNECT_PROXY_PREFIX = "connect-proxy"


@dataclass
class LogConfig:
    """Per-task log rotation policy (reference structs.go LogConfig:
    MaxFiles × MaxFileSizeMB, defaults 10 × 10)."""

    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass
class Task:
    name: str = ""
    driver: str = ""
    user: str = ""
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)
    artifacts: List[Dict[str, Any]] = field(default_factory=list)
    templates: List[Dict[str, Any]] = field(default_factory=list)
    vault: Optional[Dict[str, Any]] = None
    leader: bool = False
    # task role marker (reference structs.go TaskKind), e.g.
    # "connect-proxy:<service>" for injected sidecars
    kind: str = ""
    kill_timeout_ns: int = 5 * 10**9
    kill_signal: str = "SIGTERM"
    restart_policy: Optional[RestartPolicy] = None
    dispatch_payload_file: str = ""
    # volume_mount stanzas (reference structs.go VolumeMount)
    volume_mounts: List["VolumeMount"] = field(default_factory=list)
    log_config: LogConfig = field(default_factory=LogConfig)


@dataclass
class TaskGroup:
    name: str = ""
    count: int = 1
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    tasks: List[Task] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    networks: List[NetworkResource] = field(default_factory=list)
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    # GROUP-level services (reference structs.go TaskGroup.Services) —
    # where Consul Connect stanzas live
    services: List[Service] = field(default_factory=list)

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None


@dataclass
class PeriodicConfig:
    enabled: bool = False
    spec: str = ""
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"


@dataclass
class ParameterizedJobConfig:
    payload: str = "optional"
    meta_required: List[str] = field(default_factory=list)
    meta_optional: List[str] = field(default_factory=list)


@dataclass
class Job:
    """A job specification (reference structs.go:3285)."""

    id: str = ""
    name: str = ""
    namespace: str = "default"
    region: str = "global"
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: List[str] = field(default_factory=lambda: ["dc1"])
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    payload: bytes = b""
    meta: Dict[str, str] = field(default_factory=dict)
    stop: bool = False
    parent_id: str = ""
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    stable: bool = False
    version: int = 0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    def stopped(self) -> bool:
        return self.stop

    def namespaced_id(self) -> Tuple[str, str]:
        return (self.namespace, self.id)

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def combined_task_meta(self, tg_name: str, task_name: str) -> Dict[str, str]:
        """Job -> group -> task meta, task wins (reference Job.CombinedTaskMeta)."""
        out = dict(self.meta)
        tg = self.lookup_task_group(tg_name)
        if tg is not None:
            out.update(tg.meta)
            task = tg.lookup_task(task_name)
            if task is not None:
                out.update(task.meta)
        return out

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized is not None

    def copy(self) -> "Job":
        import copy as _copy

        return _copy.deepcopy(self)

    def derive_child(self, child_id: str) -> "Job":
        """Copy for a periodic/dispatch child: fresh indexes, runnable, not
        stable (reference periodic.go deriveJob / job_endpoint.go Dispatch)."""
        child = self.copy()
        child.id = child_id
        child.name = child_id
        child.parent_id = self.id
        child.periodic = None
        child.stop = False
        child.stable = False
        child.version = 0
        child.status = ""
        child.status_description = ""
        child.create_index = child.modify_index = child.job_modify_index = 0
        return child


# ---------------------------------------------------------------------------
# Deployment
# ---------------------------------------------------------------------------


@dataclass
class DeploymentState:
    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: List[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_ns: int = 0
    require_progress_by_ns: int = 0


@dataclass
class Deployment:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_create_index: int = 0
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def active(self) -> bool:
        return self.status in DEPLOYMENT_ACTIVE_STATUSES

    def get_id(self) -> str:
        return self.id

    def has_placed_canaries(self) -> bool:
        return any(len(s.placed_canaries) > 0 for s in self.task_groups.values())

    def requires_promotion(self) -> bool:
        return any(
            s.desired_canaries > 0 and not s.promoted for s in self.task_groups.values()
        )

    def copy(self) -> "Deployment":
        import copy as _copy

        return _copy.deepcopy(self)


@dataclass
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


def deployment_get_id(d: Optional[Deployment]) -> str:
    return d.id if d is not None else ""


# ---------------------------------------------------------------------------
# Allocation
# ---------------------------------------------------------------------------


@dataclass
class RescheduleEvent:
    reschedule_time_ns: int = 0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_ns: int = 0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)


@dataclass
class DesiredTransition:
    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return self.migrate is True

    def should_force_reschedule(self) -> bool:
        return self.force_reschedule is True


@dataclass
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp_ns: int = 0
    canary: bool = False
    modify_index: int = 0

    def is_unhealthy(self) -> bool:
        return self.healthy is False

    def is_healthy(self) -> bool:
        return self.healthy is True


@dataclass
class TaskState:
    state: str = "pending"  # pending | running | dead
    failed: bool = False
    restarts: int = 0
    started_at_ns: int = 0
    finished_at_ns: int = 0
    # event trail synced to the server (reference structs.go TaskState
    # .Events → `nomad alloc status` / UI); entries are
    # {"Type", "Message", "DisplayMessage", "Time"} dicts
    events: List[Dict[str, Any]] = field(default_factory=list)

    def successful(self) -> bool:
        return self.state == "dead" and not self.failed


@dataclass
class Allocation:
    """A placement of a task group on a node (reference structs.go:7466)."""

    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    allocated_resources: Optional[AllocatedResources] = None
    desired_status: str = ALLOC_DESIRED_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    previous_allocation: str = ""
    next_allocation: str = ""
    preempted_allocations: List[str] = field(default_factory=list)
    preempted_by_allocation: str = ""
    followup_eval_id: str = ""
    metrics: Optional["AllocMetric"] = None
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time_ns: int = 0
    modify_time_ns: int = 0

    def index(self) -> int:
        """The trailing ``[N]`` of the alloc name (reference structs.go
        AllocIndex / AllocName)."""
        l, r = self.name.rfind("["), self.name.rfind("]")
        if l == -1 or r == -1 or l >= r:
            return -1
        try:
            return int(self.name[l + 1 : r])
        except ValueError:
            return -1

    # -- status ------------------------------------------------------------

    def server_terminal_status(self) -> bool:
        return self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)

    def client_terminal_status(self) -> bool:
        return self.client_status in (
            ALLOC_CLIENT_COMPLETE,
            ALLOC_CLIENT_FAILED,
            ALLOC_CLIENT_LOST,
        )

    def terminal_status(self) -> bool:
        return self.server_terminal_status() or self.client_terminal_status()

    def ran_successfully(self) -> bool:
        if not self.task_states:
            return False
        return all(s.successful() for s in self.task_states.values())

    # -- resources ---------------------------------------------------------

    def comparable_resources(self) -> ComparableResources:
        if self.allocated_resources is not None:
            return self.allocated_resources.comparable()
        return ComparableResources()

    # -- rescheduling ------------------------------------------------------

    def reschedule_policy(self) -> Optional[ReschedulePolicy]:
        if self.job is None:
            return None
        tg = self.job.lookup_task_group(self.task_group)
        if tg is None:
            return None
        return tg.reschedule_policy

    def last_event_time_ns(self) -> int:
        """Latest task finished/started timestamp (reference :7725)."""
        last = 0
        for s in self.task_states.values():
            if s.finished_at_ns > last:
                last = s.finished_at_ns
        if last == 0:
            last = self.modify_time_ns
        return last

    def next_delay_ns(self) -> int:
        """Delay before this alloc may be rescheduled (reference :7779)."""
        policy = self.reschedule_policy()
        if policy is None:
            return 0
        delay = policy.delay_ns
        tracker = self.reschedule_tracker
        if tracker is None or not tracker.events:
            return delay
        events = tracker.events
        if policy.delay_function == "exponential":
            delay = events[-1].delay_ns * 2
        elif policy.delay_function == "fibonacci":
            if len(events) >= 2:
                fib_n1 = events[-1].delay_ns
                fib_n2 = events[-2].delay_ns
                if fib_n2 == policy.max_delay_ns and fib_n1 == policy.delay_ns:
                    delay = fib_n1
                else:
                    delay = fib_n1 + fib_n2
        else:
            return delay
        if policy.max_delay_ns > 0 and delay > policy.max_delay_ns:
            delay = policy.max_delay_ns
            time_diff = self.last_event_time_ns() - events[-1].reschedule_time_ns
            if time_diff > delay:
                delay = policy.delay_ns
        return delay

    def next_reschedule_time(self) -> Tuple[int, bool]:
        """(reschedule_time_ns, eligible) — reference :7752."""
        fail_time = self.last_event_time_ns()
        policy = self.reschedule_policy()
        if (
            self.desired_status == ALLOC_DESIRED_STOP
            or self.client_status != ALLOC_CLIENT_FAILED
            or fail_time == 0
            or policy is None
        ):
            return 0, False
        next_delay = self.next_delay_ns()
        next_time = fail_time + next_delay
        eligible = policy.unlimited or (
            policy.attempts > 0 and self.reschedule_tracker is None
        )
        if policy.attempts > 0 and self.reschedule_tracker and self.reschedule_tracker.events:
            attempted = 0
            for ev in reversed(self.reschedule_tracker.events):
                if fail_time - ev.reschedule_time_ns < policy.interval_ns:
                    attempted += 1
            eligible = attempted < policy.attempts and next_delay < policy.interval_ns
        return next_time, eligible

    def should_reschedule(self, policy: Optional[ReschedulePolicy], fail_time_ns: int) -> bool:
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return False
        if self.client_status != ALLOC_CLIENT_FAILED:
            return False
        return self.reschedule_eligible(policy, fail_time_ns)

    def reschedule_eligible(self, policy: Optional[ReschedulePolicy], fail_time_ns: int) -> bool:
        if policy is None:
            return False
        enabled = policy.attempts > 0 or policy.unlimited
        if not enabled:
            return False
        if policy.unlimited:
            return True
        if self.reschedule_tracker is None or not self.reschedule_tracker.events:
            return True
        attempted = 0
        for ev in reversed(self.reschedule_tracker.events):
            if fail_time_ns - ev.reschedule_time_ns < policy.interval_ns:
                attempted += 1
        return attempted < policy.attempts

    def copy(self) -> "Allocation":
        import copy as _copy

        return _copy.deepcopy(self)

    def copy_skip_job(self) -> "Allocation":
        """Copy sharing the (immutable) job. Must not mutate self —
        concurrent snapshot readers share this object.

        Field-wise rather than ``deepcopy``: this is the hottest copy in
        the scheduling pipeline (every alloc is copied on state-store
        insert and on the client sync path), and generic deepcopy's
        reflection over the whole object graph costs ~0.6ms per alloc —
        the dominant per-placement cost at C1M scale. Scalars/strings
        share; every mutable container is copied."""
        import copy as _copy

        c = _copy.copy(self)
        # memoized derived state must not leak onto a copy whose caller
        # may replace resources (e.g. in-place updates)
        c.__dict__.pop("_usage_vec", None)
        if self.allocated_resources is not None:
            c.allocated_resources = self.allocated_resources.copy()
        c.desired_transition = _copy.copy(self.desired_transition)
        c.task_states = (
            {k: _copy.deepcopy(v) for k, v in self.task_states.items()}
            if self.task_states else {}
        )
        if self.deployment_status is not None:
            c.deployment_status = _copy.copy(self.deployment_status)
        if self.reschedule_tracker is not None:
            c.reschedule_tracker = _copy.deepcopy(self.reschedule_tracker)
        c.preempted_allocations = list(self.preempted_allocations)
        if self.metrics is not None:
            c.metrics = self.metrics.copy()
        return c


# ---------------------------------------------------------------------------
# Alloc metrics
# ---------------------------------------------------------------------------


@dataclass
class NodeScoreMeta:
    node_id: str = ""
    scores: Dict[str, float] = field(default_factory=dict)
    norm_score: float = 0.0


@dataclass
class AllocMetric:
    """Scheduling diagnostics carried on each alloc (reference structs.go:8035)."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    score_meta: List[NodeScoreMeta] = field(default_factory=list)
    allocation_time_ns: int = 0
    coalesced_failures: int = 0
    # transient scratch, not serialized
    _topk: int = 5

    def evaluate_node(self) -> None:
        self.nodes_evaluated += 1

    def filter_node(self, node: Optional[Node], reason: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = self.class_filtered.get(node.node_class, 0) + 1
        if reason:
            self.constraint_filtered[reason] = self.constraint_filtered.get(reason, 0) + 1

    def exhausted_node(self, node: Optional[Node], dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = self.class_exhausted.get(node.node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = self.dimension_exhausted.get(dimension, 0) + 1

    def score_node(self, node: Optional[Node], name: str, score: float) -> None:
        if node is None:
            return
        for m in self.score_meta:
            if m.node_id == node.id:
                m.scores[name] = score
                if name == "normalized-score":
                    m.norm_score = score
                return
        m = NodeScoreMeta(node_id=node.id, scores={name: score})
        if name == "normalized-score":
            m.norm_score = score
        self.score_meta.append(m)

    def populate_score_meta_data(self) -> None:
        """Keep only the top-K scored nodes (reference uses a kheap of 5)."""
        self.score_meta.sort(key=lambda m: m.norm_score, reverse=True)
        del self.score_meta[self._topk :]

    def copy(self) -> "AllocMetric":
        import copy as _copy

        c = _copy.copy(self)
        c.nodes_available = dict(self.nodes_available)
        c.class_filtered = dict(self.class_filtered)
        c.constraint_filtered = dict(self.constraint_filtered)
        c.class_exhausted = dict(self.class_exhausted)
        c.dimension_exhausted = dict(self.dimension_exhausted)
        c.quota_exhausted = list(self.quota_exhausted)
        c.score_meta = [
            NodeScoreMeta(m.node_id, dict(m.scores), m.norm_score)
            for m in self.score_meta
        ]
        return c


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


@dataclass
class Evaluation:
    """A scheduling trigger (reference structs.go:8352)."""

    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    priority: int = JOB_DEFAULT_PRIORITY
    type: str = JOB_TYPE_SERVICE
    triggered_by: str = EVAL_TRIGGER_JOB_REGISTER
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_ns: int = 0
    wait_until_ns: int = 0
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    leader_ack: str = ""
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time_ns: int = 0
    modify_time_ns: int = 0
    # distributed-trace context ({"trace_id", "span_id"}) carried with
    # the eval through raft and RPC so one trace_id follows submit ->
    # broker -> (possibly remote) worker -> plan apply -> ack
    trace_ctx: Optional[Dict[str, str]] = None

    def __post_init__(self) -> None:
        if self.trace_ctx is None:
            # stamp the ambient trace at CREATION: an eval minted inside
            # an RPC handler span (Job.Register) or by a scheduler
            # processing a traced eval (follow-up/blocked evals) inherits
            # that trace. Deterministic across replicas — the stamp rides
            # the raft log; FSM-side decode passes trace_ctx explicitly.
            # Deferred import: structs is the data layer, loaded long
            # before the trace package.
            from ..trace import context as _trace_context

            self.trace_ctx = _trace_context.inject()

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED, EVAL_STATUS_CANCELLED)

    def should_enqueue(self) -> bool:
        if self.status == EVAL_STATUS_PENDING:
            return True
        if self.status in (
            EVAL_STATUS_COMPLETE,
            EVAL_STATUS_FAILED,
            EVAL_STATUS_BLOCKED,
            EVAL_STATUS_CANCELLED,
        ):
            return False
        raise ValueError(f"unhandled evaluation ({self.id}) status {self.status}")

    def should_block(self) -> bool:
        if self.status == EVAL_STATUS_BLOCKED:
            return True
        if self.status in (
            EVAL_STATUS_COMPLETE,
            EVAL_STATUS_FAILED,
            EVAL_STATUS_PENDING,
            EVAL_STATUS_CANCELLED,
        ):
            return False
        raise ValueError(f"unhandled evaluation ({self.id}) status {self.status}")

    def make_plan(self, job: Optional[Job]) -> "Plan":
        p = Plan(
            eval_id=self.id,
            priority=self.priority,
            job=job,
        )
        if job is not None:
            p.all_at_once = job.all_at_once
        return p

    def next_rolling_eval(self, wait_ns: int) -> "Evaluation":
        now = now_ns()
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_ns=wait_ns,
            previous_eval=self.id,
            create_time_ns=now,
            modify_time_ns=now,
        )

    def create_blocked_eval(
        self,
        class_eligibility: Optional[Dict[str, bool]],
        escaped: bool,
        quota_reached: str,
    ) -> "Evaluation":
        now = now_ns()
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_QUEUED_ALLOCS,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=class_eligibility or {},
            escaped_computed_class=escaped,
            quota_limit_reached=quota_reached,
            create_time_ns=now,
            modify_time_ns=now,
        )

    def create_failed_follow_up_eval(self, wait_ns: int) -> "Evaluation":
        now = now_ns()
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_FAILED_FOLLOW_UP,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_ns=wait_ns,
            previous_eval=self.id,
            create_time_ns=now,
            modify_time_ns=now,
        )

    def update_modify_time(self) -> None:
        now = now_ns()
        self.modify_time_ns = max(now, self.create_time_ns + 1)

    def copy(self) -> "Evaluation":
        import copy as _copy

        return _copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


@dataclass
class DesiredUpdates:
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass
class PlanAnnotations:
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    preempted_allocs: List[Allocation] = field(default_factory=list)


@dataclass
class DenseTGPlacements:
    """A block of fresh placements of ONE task group kept as parallel
    arrays end to end: device scan -> plan submit -> plan apply -> FSM
    upsert. The TPU-native answer to the reference's per-alloc object
    flow (generic_sched.go:497-518 builds one Allocation per placement;
    plan_apply.go:324-336 already normalizes alloc DIFFS on the wire —
    this design goes further and defers materializing Allocation objects
    entirely until something reads them).

    Every placement in a block shares the job, task group, eval,
    deployment and — because the dense path only engages for task groups
    with no network or device asks — the exact AllocatedResources shape
    (``resources_proto``). Per-placement state is just the parallel
    lists: id, name, node, score, nodes-evaluated. ``materialize(i)``
    builds (and caches) the classic Allocation object on read; the cache
    lives outside the dataclass fields so wire/raft codecs never ship it.
    """

    namespace: str = "default"
    job_id: str = ""
    task_group: str = ""
    eval_id: str = ""
    deployment_id: str = ""
    job: Optional[Job] = None
    resources_proto: Optional[AllocatedResources] = None
    # capacity ask of ONE placement: (cpu, mem_mb, disk_mb, mbits) — the
    # plan applier's vectorized re-check and the state store's usage
    # mirror consume this instead of per-alloc comparable_resources()
    ask_vec: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    ids: List[str] = field(default_factory=list)
    names: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)
    node_names: List[str] = field(default_factory=list)
    scores: List[float] = field(default_factory=list)
    nodes_evaluated: List[int] = field(default_factory=list)
    nodes_available: Dict[str, int] = field(default_factory=dict)
    # per-placement preempted alloc ids (device-side preemption engine,
    # tpu/preempt.py); empty when the block preempts nothing — the
    # common case, so the wire cost is one empty list
    preempted: List[List[str]] = field(default_factory=list)
    create_index: int = 0
    modify_index: int = 0
    create_time_ns: int = 0

    def __len__(self) -> int:
        return len(self.ids)

    def __getstate__(self):
        # lazy caches never ship (pickle path; the wire codec already
        # serializes declared fields only)
        d = self.__dict__.copy()
        d.pop("_mat", None)
        d.pop("_by_node", None)
        d.pop("_by_id", None)
        return d

    def id_index_map(self) -> Dict[str, int]:
        """alloc id -> slot (cached; blocks are immutable once committed)."""
        m = self.__dict__.get("_by_id")
        if m is None:
            m = {aid: i for i, aid in enumerate(self.ids)}
            self.__dict__["_by_id"] = m
        return m

    def key(self) -> str:
        """Store-level block key (ids are unique, blocks are non-empty)."""
        return self.ids[0] if self.ids else ""

    def stamp(self, index: int, timestamp_ns: int) -> None:
        """Index-stamp at FSM apply; invalidates any materialization made
        against a provisional (optimistic-snapshot) stamp."""
        self.create_index = index
        self.modify_index = index
        if timestamp_ns:
            self.create_time_ns = timestamp_ns
        self.__dict__.pop("_mat", None)

    def clone_for_snapshot(self) -> "DenseTGPlacements":
        """Shallow copy sharing the (immutable-once-built) parallel
        arrays but NOT the lazy ``_mat`` cache. The optimistic plan
        applier folds the COPY into its snapshot while the original
        rides the raft payload into the live FSM store: the FSM's
        commit stamp would otherwise mutate index fields and pop the
        cache on an object that concurrent snapshot readers are
        materializing against."""
        c = object.__new__(DenseTGPlacements)
        c.__dict__.update(self.__dict__)
        c.__dict__.pop("_mat", None)
        return c

    def node_index_map(self) -> Dict[str, List[int]]:
        """node_id -> placement indices (cached; blocks are immutable
        once committed)."""
        m = self.__dict__.get("_by_node")
        if m is None:
            m = {}
            for i, nid in enumerate(self.node_ids):
                m.setdefault(nid, []).append(i)
            self.__dict__["_by_node"] = m
        return m

    def materialize(self, i: int) -> Allocation:
        cache = self.__dict__.get("_mat")
        if cache is None:
            cache = self.__dict__["_mat"] = [None] * len(self.ids)
        a = cache[i]
        if a is None:
            score = self.scores[i] if i < len(self.scores) else 0.0
            metrics = AllocMetric(
                nodes_evaluated=(
                    self.nodes_evaluated[i] if i < len(self.nodes_evaluated) else 0
                ),
                nodes_available=self.nodes_available,
                score_meta=[
                    NodeScoreMeta(
                        node_id=self.node_ids[i],
                        scores={"binpack": score, "normalized-score": score},
                        norm_score=score,
                    )
                ],
            )
            a = Allocation(
                id=self.ids[i],
                namespace=self.namespace,
                eval_id=self.eval_id,
                name=self.names[i],
                node_id=self.node_ids[i],
                node_name=self.node_names[i],
                job_id=self.job_id,
                job=self.job,
                task_group=self.task_group,
                allocated_resources=self.resources_proto,
                desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_PENDING,
                deployment_id=self.deployment_id,
                metrics=metrics,
                create_index=self.create_index,
                modify_index=self.modify_index,
                create_time_ns=self.create_time_ns,
                modify_time_ns=self.create_time_ns,
            )
            # every placement in the block shares ask_vec by construction
            a.__dict__["_usage_vec"] = self.ask_vec
            if self.preempted and i < len(self.preempted) and self.preempted[i]:
                a.preempted_allocations = list(self.preempted[i])
            cache[i] = a
        return a

    def select(self, keep: List[int]) -> "DenseTGPlacements":
        """Sub-block of the given placement indices (plan applier partial
        commit)."""
        return DenseTGPlacements(
            namespace=self.namespace,
            job_id=self.job_id,
            task_group=self.task_group,
            eval_id=self.eval_id,
            deployment_id=self.deployment_id,
            job=self.job,
            resources_proto=self.resources_proto,
            ask_vec=self.ask_vec,
            ids=[self.ids[i] for i in keep],
            names=[self.names[i] for i in keep],
            node_ids=[self.node_ids[i] for i in keep],
            node_names=[self.node_names[i] for i in keep],
            scores=[self.scores[i] for i in keep] if self.scores else [],
            nodes_evaluated=(
                [self.nodes_evaluated[i] for i in keep] if self.nodes_evaluated else []
            ),
            nodes_available=self.nodes_available,
            preempted=(
                [self.preempted[i] for i in keep] if self.preempted else []
            ),
        )


@dataclass
class Plan:
    """A proposed set of mutations, submitted to the leader (reference structs.go:8645)."""

    eval_id: str = ""
    eval_token: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    job: Optional[Job] = None
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    annotations: Optional[PlanAnnotations] = None
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    # dense placement blocks (DenseTGPlacements): fresh placements that
    # never materialize per-alloc objects on the commit path
    dense_placements: List[DenseTGPlacements] = field(default_factory=list)
    snapshot_index: int = 0
    # Scheduler opt-in to the asynchronous eval-lifecycle pipeline
    # (nomad_tpu/pipeline): the submitting worker may hand commit + ack
    # to the async applier instead of blocking on the plan future. Only
    # set on device-built plans whose success the scheduler does not
    # need to inspect before completing the eval.
    async_ok: bool = False

    def dense_count(self) -> int:
        return sum(len(b.ids) for b in self.dense_placements)

    def append_stopped_alloc(
        self, alloc: Allocation, desired_desc: str, client_status: str = ""
    ) -> None:
        """Reference Plan.AppendStoppedAlloc (structs.go:8707)."""
        new_alloc = alloc.copy_skip_job()
        if self.job is None and alloc.job is not None:
            self.job = alloc.job
        new_alloc.job = None
        new_alloc.desired_status = ALLOC_DESIRED_STOP
        new_alloc.desired_description = desired_desc
        if client_status:
            new_alloc.client_status = client_status
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def append_preempted_alloc(self, alloc: Allocation, preempting_alloc_id: str) -> None:
        new_alloc = Allocation(
            id=alloc.id,
            job_id=alloc.job_id,
            namespace=alloc.namespace,
            node_id=alloc.node_id,
            desired_status=ALLOC_DESIRED_EVICT,
            preempted_by_allocation=preempting_alloc_id,
            desired_description=f"Preempted by alloc ID {preempting_alloc_id}",
            allocated_resources=alloc.allocated_resources,
            task_group=alloc.task_group,
        )
        self.node_preemptions.setdefault(alloc.node_id, []).append(new_alloc)

    def pop_update(self, alloc: Allocation) -> None:
        existing = self.node_update.get(alloc.node_id, [])
        if existing and existing[-1].id == alloc.id:
            existing.pop()
            if not existing:
                self.node_update.pop(alloc.node_id, None)

    def append_alloc(self, alloc: Allocation) -> None:
        alloc.job = None
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def is_noop(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.dense_placements
            and self.deployment is None
            and not self.deployment_updates
        )

    def inflate_dense(self) -> None:
        """Materialize dense blocks into ``node_allocation`` (test
        harness / compatibility consumers; the production plan applier
        keeps blocks dense end to end)."""
        for block in self.dense_placements:
            for i in range(len(block.ids)):
                alloc = block.materialize(i)
                self.node_allocation.setdefault(alloc.node_id, []).append(alloc)
        self.dense_placements = []


@dataclass
class PlanResult:
    """What the leader committed (reference structs.go:8819)."""

    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    dense_placements: List[DenseTGPlacements] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def is_noop(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.dense_placements
            and not self.deployment_updates
            and self.deployment is None
        )

    def full_commit(self, plan: Plan) -> Tuple[bool, int, int]:
        expected = 0
        actual = 0
        for node, alloc_list in plan.node_allocation.items():
            expected += len(alloc_list)
            actual += len(self.node_allocation.get(node, []))
        expected += plan.dense_count()
        actual += sum(len(b.ids) for b in self.dense_placements)
        return actual == expected, expected, actual


# ---------------------------------------------------------------------------
# Operator / scheduler configuration
# ---------------------------------------------------------------------------


SCHED_ALG_BINPACK = "binpack"
SCHED_ALG_TPU_BINPACK = "tpu_binpack"
SCHED_ALG_TPU_BINPACK_CHUNKED = "tpu_binpack_chunked"


@dataclass
class PreemptionConfig:
    system_scheduler_enabled: bool = True
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    """Runtime-mutable scheduler config (reference structs/operator.go:124).

    ``scheduler_algorithm`` selects the placement backend:
    ``binpack`` = host iterator pipeline (parity oracle),
    ``tpu_binpack`` = batched JAX engine (the default, bit-identical
    to the host oracle),
    ``tpu_binpack_chunked`` = chunked top-K throughput tier: up to
    ``chunk_k`` placements of one task group per scan step, validated
    by sampled parity (``parity_sample_rate``) instead of bit parity.
    Preempting and otherwise chunk-ineligible evals silently fall back
    to the bit-parity scan.
    """

    scheduler_algorithm: str = SCHED_ALG_TPU_BINPACK
    chunk_k: int = 128
    parity_sample_rate: float = 0.05
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    create_index: int = 0
    modify_index: int = 0


@dataclass
class QueryOptions:
    """Read-RPC options (reference structs/structs.go QueryOptions).

    ``min_query_index`` > 0 turns the read into a blocking query: the
    server parks the request until the target table moves past that
    index or ``max_query_time`` elapses. ``allow_stale`` lets any
    server — leader or follower — answer from its local FSM instead of
    forwarding to the leader.
    """

    min_query_index: int = 0
    max_query_time: float = 0.0
    allow_stale: bool = False


@dataclass
class QueryMeta:
    """Response metadata stamped on every read served with QueryOptions
    (reference structs/structs.go QueryMeta).

    ``index`` is the state-store index the result is consistent with —
    clients chain it back as the next ``min_query_index``.
    ``follower_lag_ms`` is only meaningful on stale reads: how far
    behind the leader's heartbeat stream this replica was when it
    answered.
    """

    index: int = 0
    known_leader: bool = False
    last_contact_ms: float = 0.0
    follower_lag_ms: float = 0.0
