"""TPU placement engine: dense tensor encodings + jit'd scoring."""
