"""DeviceBatcher: gathers concurrent evals into ONE device dispatch.

The production realization of SURVEY §2.6 row 1 — the TPU-native analog of
the reference's N scheduler workers per server (nomad/server.go:1307
setupWorkers, worker.go:244). Host workers still dequeue and run the
scheduler logic concurrently; when each reaches its placement step it
submits an ``EncodedEval`` here and blocks. A dispatcher thread gathers the
requests that arrive within a small window, pads them to shared bucketed
shapes, stacks them along a leading eval axis and runs the eval-batched
scan (engine._build_batched_scan) — one device dispatch for the whole
batch, amortizing host→device transfer and dispatch latency, and sharding
over the ("evals", "nodes") mesh when one is configured.

Per-eval semantics are untouched: the batched scan vmaps the exact
single-eval parity scan, so each eval's plan is identical to what the
single dispatch produces; cross-eval conflicts resolve in the plan applier
exactly as with the reference's optimistically-concurrent workers.
"""
from __future__ import annotations

import logging
import queue
import threading
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..chaos.injector import fire as chaos_fire
from .engine import EncodedEval, _build_batched_scan, _round_up
from .intscore import E27_ONE as _E27_NEUTRAL
from ..utils.lock_witness import witness_lock
from ..utils.race_witness import tracked_dict

logger = logging.getLogger("nomad_tpu.tpu.batcher")

# every constructed batcher, weakly held, so the engine's atexit
# shutdown path (TpuPlacementEngine.shutdown) can stop dispatcher and
# warm-compile threads deterministically instead of letting interpreter
# teardown race them into the runtime (the multichip dryrun's rc 139)
_LIVE: "weakref.WeakSet" = weakref.WeakSet()


def shutdown_all() -> None:
    """Stop every live batcher and join its warm-compile threads."""
    for b in list(_LIVE):
        try:
            b.stop()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            logger.debug("batcher stop failed at shutdown", exc_info=True)


def _pow2ceil(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


def assert_chunk_gate(enc: EncodedEval) -> None:
    """Dispatch-side re-assertion of the chunked tier's eligibility gate
    (engine._chunk_eligible decides routing; this catches a bypass).

    The chunk step has NO eviction scoring and passes the preemption
    carry through untouched, so a preempting or destructive eval reaching
    chunked dispatch would silently drop its evictions — the
    deficit-carry would then re-ask for capacity the preemption was
    supposed to free, over-placing on retry rounds. Such evals must fall
    back to the bit-parity scan.
    """
    assert enc.pre_allocs is None, (
        "chunked tier dispatched a preempting eval (pre_allocs present); "
        "preemption must take the bit-parity scan"
    )
    assert not (np.asarray(enc.xs[2]) >= 0).any(), (
        "chunked tier dispatched an eval with eviction steps; "
        "destructive updates must take the bit-parity scan"
    )


def pad_encoded(enc: EncodedEval, n_pad: int, g_pad: int, s_pad: int,
                v_pad: int, p_pad: int, dtype,
                d_pad: int = 0, k_pad: Optional[int] = None,
                aff_pad: Optional[int] = None,
                evd_pad: Optional[int] = None,
                fac_pad: Optional[int] = None,
                dpd_pad: Optional[int] = None,
                dpv_pad: Optional[int] = None,
                fnd_pad: Optional[int] = None,
                prec_pad: Optional[int] = None,
                pregp_pad: Optional[int] = None) -> Tuple[tuple, tuple, tuple]:
    """Pad one eval's arrays to the batch's shared bucketed dims.

    Padding is semantically inert by construction:
      - nodes beyond n_real are infeasible and outside the ring window
      - task-group slots >= g are born with failed=True in the carry
      - placement steps beyond p index a padded (pre-failed) TG slot, so
        the scan body skips them (skip_step) and mutates nothing
      - spread rows beyond s are inactive; the invalid vocab bucket is
        remapped from v-1 to v_pad-1
      - capacity dims beyond the eval's own (device dims of co-batched
        device jobs) pad zero ask against zero totals: 0 <= 0 fits
    """
    (totals, reserved, asks, feat_packed, aff_score, desired_counts,
     dh_job, dh_tg, limits, spread_vids, spread_desired, spread_weights,
     spread_has_targets, spread_active, sum_spread_weights, n_real,
     e_ask, dp_vids, dp_limit, dp_applies,
     pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf) = enc.static
    (used0, tg_counts0, job_counts0, spread_counts0, spread_entry0,
     offset0, failed0, e_base0, dp_counts0,
     pre_alive0, pre_remaining0, pre_counts0) = enc.carry
    (tg_idx, penalty_idx, evict_node, evict_res, evict_tg,
     limit_p, sum_sw_p, ev_factor, rev_factor, forced_node) = enc.xs

    n0, g0, s0, v0, p0 = enc.n_pad, enc.g, enc.s, enc.v, enc.p
    d0 = totals.shape[1]
    if d_pad <= 0:
        d_pad = d0
    if k_pad is None:
        k_pad = penalty_idx.shape[1]
    if aff_pad is None:
        aff_pad = aff_score.shape[0]
    if evd_pad is None:
        evd_pad = evict_res.shape[1]
    if fac_pad is None:
        fac_pad = ev_factor.shape[1]
    if dpd_pad is None:
        dpd_pad = dp_vids.shape[0]
    if dpv_pad is None:
        dpv_pad = dp_counts0.shape[1]
    if fnd_pad is None:
        fnd_pad = forced_node.shape[1]
    if prec_pad is None:
        prec_pad = pre_res.shape[1]
    if pregp_pad is None:
        pregp_pad = pre_counts0.shape[0]
    dn, dg, ds, dv, dp = (n_pad - n0, g_pad - g0, s_pad - s0,
                          v_pad - v0, p_pad - p0)
    dd = d_pad - d0
    assert min(dn, dg, ds, dv, dp, dd) >= 0
    assert k_pad >= penalty_idx.shape[1] and aff_pad >= aff_score.shape[0]
    assert dp == 0 or g_pad > g0  # padded steps need a pre-failed TG slot

    def pad(arr, widths, fill=0):
        if all(w == (0, 0) for w in widths):
            return np.asarray(arr, dtype=arr.dtype)
        return np.pad(arr, widths, constant_values=fill)

    f = lambda a: np.asarray(a, dtype)  # noqa: E731 — common float cast

    # spread_vids: remap this eval's invalid bucket (v0-1) onto the shared
    # one (v_pad-1) BEFORE padding, then pad new cells as invalid too
    vids = np.where(spread_vids >= v0 - 1, v_pad - 1, spread_vids)
    vids = pad(vids, ((0, dg), (0, ds), (0, dn)), v_pad - 1)

    static = (
        pad(f(totals), ((0, dn), (0, dd))),
        # int-mode evals fold reserved into totals and pass it ZERO-height
        # (rows only — the D axis must still pad so the batch stacks)
        pad(f(reserved), ((0, dn if reserved.shape[0] else 0), (0, dd))),
        pad(f(asks), ((0, dg), (0, dd))),
        # packed feature plane (intscore.pack_feat_planes): padded TG rows
        # and padded nodes get 0 = infeasible with no affinity lane
        pad(feat_packed, ((0, dg), (0, dn)), 0),
        # aff_score may have a ZERO G axis (shape-specialized absent
        # affinities): the batch target is 0 when every co-batched eval
        # lacks affinities (keeping the specialization), else g_pad —
        # padded zero rows are inert either way
        pad(f(aff_score), ((0, aff_pad - aff_score.shape[0]), (0, dn))),
        pad(desired_counts, ((0, dg),), 1),
        pad(dh_job, ((0, dg),), False),
        pad(dh_tg, ((0, dg),), False),
        pad(limits, ((0, dg),), 0),
        vids.astype(np.int32),
        pad(f(spread_desired), ((0, dg), (0, ds), (0, dv)), -1.0),
        pad(f(spread_weights), ((0, dg), (0, ds))),
        pad(spread_has_targets, ((0, dg), (0, ds)), False),
        pad(spread_active, ((0, dg), (0, ds)), False),
        pad(f(sum_spread_weights), ((0, dg),)),
        np.int32(n_real),
        # Q27 exponential ask factors (int mode; zero-sized in float
        # batches). Padded cells get the neutral factor — padded nodes
        # are infeasible and padded TG slots pre-failed anyway.
        pad(e_ask, ((0, (g_pad - e_ask.shape[0]) if e_ask.shape[0] else 0),
                    (0, (n_pad - e_ask.shape[1]) if e_ask.shape[0] else 0),
                    (0, 0)), _E27_NEUTRAL),
        # distinct_property: remap this eval's MISSING bucket onto the
        # batch's (dpv_pad-1) before padding; padded constraint rows
        # apply to no TG
        pad(
            np.where(dp_vids >= dp_counts0.shape[1] - 1, dpv_pad - 1, dp_vids)
            if dp_vids.shape[0] else dp_vids.reshape(0, n0),
            ((0, dpd_pad - dp_vids.shape[0]), (0, dn)), dpv_pad - 1,
        ),
        pad(dp_limit, ((0, dpd_pad - dp_limit.shape[0]),), 1),
        pad(dp_applies, ((0, dg), (0, dpd_pad - dp_applies.shape[1])), False),
        # preemption candidate axis (tpu/preempt.py): ZERO-width when no
        # co-batched eval preempts (the step's eviction block compiles
        # away); mixed batches widen with inert slots — eligibility stays
        # False, so the greedy pass never takes them and pre_met stays
        # False (cap_ok falls back to fits) for widened evals
        pad(pre_res, ((0, dn), (0, prec_pad - pre_res.shape[1]), (0, 0)), 0),
        pad(pre_prio, ((0, dn), (0, prec_pad - pre_prio.shape[1])), 0),
        pad(pre_elig, ((0, dn), (0, prec_pad - pre_elig.shape[1])), False),
        pad(pre_mp, ((0, dn), (0, prec_pad - pre_mp.shape[1])), 0),
        pad(pre_gid, ((0, dn), (0, prec_pad - pre_gid.shape[1])), 0),
        pad(pre_evf, ((0, dn), (0, prec_pad - pre_evf.shape[1]), (0, 0)),
            _E27_NEUTRAL),
    )
    carry = (
        pad(f(used0), ((0, dn), (0, dd))),
        pad(tg_counts0, ((0, dg), (0, dn)), 0),
        pad(job_counts0, ((0, dn),), 0),
        pad(f(spread_counts0), ((0, dg), (0, ds), (0, dv))),
        pad(spread_entry0, ((0, dg), (0, ds), (0, dv)), False),
        np.int32(offset0),
        # padded TG slots are pre-failed -> padded steps are no-ops
        pad(failed0, ((0, dg),), True),
        pad(e_base0, ((0, dn if e_base0.shape[0] else 0), (0, 0)),
            _E27_NEUTRAL),
        pad(dp_counts0, ((0, dpd_pad - dp_counts0.shape[0]),
                         (0, dpv_pad - dp_counts0.shape[1])), 0),
        pad(pre_alive0, ((0, dn), (0, prec_pad - pre_alive0.shape[1])), False),
        # pre_remaining rides a zero-HEIGHT row axis when this eval has no
        # candidate tables; a preempt batch needs full rows (zeros inert:
        # widened evals' eligibility is all-False)
        (pad(pre_remaining0, ((0, dn), (0, 0)), 0)
         if pre_remaining0.shape[0]
         else np.zeros((n_pad if prec_pad else 0, 3), np.int64)),
        pad(pre_counts0, ((0, pregp_pad - pre_counts0.shape[0]),), 0),
    )
    xs = (
        pad(tg_idx, ((0, dp),), g0),  # g0 = first padded (pre-failed) slot
        # K axis may be zero (no reschedule history) — pad to the batch's
        # K with -1 sentinels, which match nothing
        pad(penalty_idx, ((0, dp), (0, k_pad - penalty_idx.shape[1])), -1),
        pad(evict_node, ((0, dp),), -1),
        # eviction axes may be ZERO-width (no destructive updates in the
        # whole batch — the step's evict path compiles away); a mixed
        # batch widens with inert fills (evict_node stays -1)
        pad(f(evict_res), ((0, dp), (0, evd_pad - evict_res.shape[1]))),
        pad(evict_tg, ((0, dp),), -1),
        pad(limit_p, ((0, dp),), 0),
        pad(f(sum_sw_p), ((0, dp),), 1.0),
        pad(ev_factor, ((0, dp), (0, fac_pad - ev_factor.shape[1])), _E27_NEUTRAL),
        pad(rev_factor, ((0, dp), (0, fac_pad - rev_factor.shape[1])), _E27_NEUTRAL),
        pad(forced_node, ((0, dp), (0, fnd_pad - forced_node.shape[1])), -1),
    )
    return static, carry, xs


class _Request:
    __slots__ = ("enc", "event", "result", "error", "t_enqueue")

    def __init__(self, enc: EncodedEval) -> None:
        self.enc = enc
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        import time

        self.t_enqueue = time.monotonic()


class DeviceBatcher:
    """Gather-window batcher in front of the eval-batched placement scan.

    ``run(enc)`` blocks the calling worker until its eval's slice of the
    batched result is ready. The dispatcher thread starts lazily on first
    use and stops with ``stop()``.
    """

    def __init__(self, max_batch: int = 8, window_ms: float = 1.0,
                 mesh=None, idle_ms: float = 0.0,
                 queue_max: int = 4096) -> None:
        self.max_batch = max(1, int(max_batch))
        self.window_s = max(0.0, float(window_ms)) / 1000.0
        # Adaptive gather: with idle_ms > 0 the batch keeps growing while
        # requests keep ARRIVING within idle_ms of each other (encode of a
        # burst trickles evals in), dispatching when the stream pauses;
        # window_ms then acts as the total cap rather than a workload-
        # tuned constant. 0 = fixed-window behavior.
        self.idle_s = max(0.0, float(idle_ms)) / 1000.0
        self.mesh = mesh
        # Bounded request queue: the async pipeline lets encode run ahead
        # of dispatch, so the gather queue needs a ceiling — a wedged
        # dispatcher must surface as worker backpressure (blocking put),
        # not unbounded growth. The default is generous (orders of
        # magnitude above worker count); queue_max <= 0 means unbounded.
        self.queue_max = int(queue_max)
        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=max(0, self.queue_max)
        )
        self._scan = None
        self._scan_lock = witness_lock("batcher.DeviceBatcher._scan_lock")  # prewarm + dispatcher race
        # padded-shape key -> set of batch buckets already compiled/warming
        self._warmed: Dict[tuple, set] = {}
        self._warm_threads: List[threading.Thread] = []
        self._lock = witness_lock("batcher.DeviceBatcher._lock")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # observability — the server publishes these as
        # nomad.device_batcher.* gauges in its stats sweep (/v1/metrics).
        # Written by the dispatcher thread AND by scheduler workers on the
        # forced-kernel path (engine.compute_system_placements), so every
        # read-modify-write takes _lock (enforced by nomad-lint).
        self.stats = tracked_dict("batcher.DeviceBatcher.stats", {  # guarded-by: _lock
            "dispatches": 0,
            "evals": 0,
            "max_batch_seen": 0,
            "padded_evals": 0,
            # wave-fill accounting: gathers = gather rounds closed,
            # full_gathers = rounds that filled max_batch. The r05 DNF
            # shipped 21 dispatches averaging ~16 evals against a 64 cap
            # with nothing recording the fill ratio; bench stamps these
            # on every config artifact now.
            "gathers": 0,
            "full_gathers": 0,
            # gather-window latency (enqueue -> dispatch start), the
            # quantity the adaptive idle gap bounds: an operator watching
            # /v1/metrics sees directly whether batching is adding
            # scheduling latency (VERDICT r4 weak #6)
            "gather_wait_ms_total": 0.0,
            "gather_wait_ms_max": 0.0,
            # per-dispatch timing split (ISSUE 4 device profiling hooks):
            # host pad/stack vs device compute (scan + block_until_ready)
            # vs D2H transfer (np.asarray), feeding dispatch_profile()'s
            # roofline note
            "pad_stack_ms_total": 0.0,
            "compute_ms_total": 0.0,
            "transfer_ms_total": 0.0,
            "d2h_bytes_total": 0,
        })
        # Demand-aware gather (guarded-by: _lock): workers announce an
        # encode-in-flight destined for this batcher via expect(); the
        # gather loop keeps its window open while announced encodes are
        # still en route instead of breaking on a fixed idle gap. Armed
        # lazily on the first expect() so raw batchers (unit tests,
        # forced-kernel paths that never announce) keep the classic
        # window/idle semantics.
        self._expected = 0
        self._demand_aware = False
        _LIVE.add(self)

    # -- lifecycle -------------------------------------------------------

    def _ensure_started(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._dispatch_loop, name="device-batcher",
                    daemon=True,
                )
                self._thread.start()

    def stop(self, timeout: Optional[float] = 5) -> None:
        """Stop the dispatcher and join warm-compile threads. The default
        bounded join keeps production/atexit shutdown from hanging on a
        wedged compile; pass timeout=None for a DETERMINISTIC full join
        (the multichip dryrun's clean-exit contract — a prewarm thread
        still inside the runtime at interpreter teardown segfaults)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        # join outstanding warm-compile threads: a prewarm mid-compile at
        # interpreter teardown segfaults inside the runtime
        self.wait_warm(timeout=timeout)
        # release anyone still parked
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = RuntimeError("device batcher stopped")
            req.event.set()

    # -- worker-facing ---------------------------------------------------

    def queue_depth(self) -> int:
        """Requests gathered but not yet dispatched — the pipeline's
        dispatch-stage depth gauge (published as
        nomad.pipeline.batcher_queue_depth in the server stats sweep)."""
        return self._queue.qsize()

    def has_warmed(self) -> bool:
        """True once at least one batch has dispatched — i.e. compile
        buckets exist and a follow-up eval of a seen shape pays only the
        padded-step cost. The engine's warm-bucket retry gate
        (compute_placements) reroutes small OCC retries here."""
        with self._lock:
            return self.stats["dispatches"] > 0

    def expect(self, n: int = 1) -> None:
        """Announce ``n`` encodes in flight that will submit here. The
        gather loop holds its window open (up to window_ms) while
        announced work is still en route, so a cohort of concurrently
        encoding evals forms ONE full wave instead of fragmenting on the
        idle gap. Every expect() must be balanced by run(expected=True)
        or cancel_expected() — the engine's dispatch path does this in a
        try/finally; a leaked expectation costs at most one window_ms cap
        per gather, never a hang."""
        with self._lock:
            self._demand_aware = True
            self._expected += n

    def cancel_expected(self) -> None:
        """Withdraw one expect() (encode fell back to the host path,
        rerouted to the chunked tier, or raised)."""
        with self._lock:
            self._expected = max(0, self._expected - 1)

    def _expected_now(self) -> int:
        with self._lock:
            return self._expected if self._demand_aware else -1

    def run(self, enc: EncodedEval, expected: bool = False):
        """Submit one encoded eval; blocks until its results are ready.
        Returns (chosen, scores, pulls, skipped, evict) numpy arrays of
        length enc.p (already sliced back from the padded batch).

        ``expected=True`` consumes one prior expect() announcement
        (arrival: the demand token converts into a queued request).

        Robust against a concurrent stop(): the wait loop re-ensures the
        dispatcher is alive, so a request that slipped into the queue
        after stop() drained it is picked up by the restarted thread
        rather than parking its worker forever."""
        if expected:
            # release the demand token before anything that can raise:
            # a chaos-failed dispatch must not leave a phantom
            # expectation holding future gathers open
            self.cancel_expected()
        # chaos hook: a fault here is a failed/slow device round trip for
        # THIS eval — the engine's dispatch guard reroutes it to the host
        # iterator path (parity-identical placements, reference latency)
        chaos_fire("device_dispatch", evals=enc.p)
        self._ensure_started()
        req = _Request(enc)
        self._queue.put(req)
        while not req.event.wait(timeout=0.5):
            self._ensure_started()
        if req.error is not None:
            raise req.error
        return req.result

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [first]
            if self.window_s > 0 and self.max_batch > 1:
                import time

                deadline = time.monotonic() + self.window_s
                while len(batch) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    # adaptive mode waits only as long as the arrival gap
                    wait = min(remaining, self.idle_s) if self.idle_s else remaining
                    # demand-aware: while announced encodes are still en
                    # route, keep polling up to the window cap instead of
                    # closing the wave on an arrival gap — this is what
                    # turns a trickling 64-eval cohort into ONE dispatch
                    demand = self._expected_now()
                    if demand > 0:
                        wait = min(remaining, max(wait, 0.02))
                    try:
                        batch.append(self._queue.get(timeout=wait))
                    except queue.Empty:
                        if demand > 0 and self._expected_now() > 0:
                            continue  # encodes still en route
                        break  # stream paused (or window expired)
            else:
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._queue.get_nowait())
                    except queue.Empty:
                        break
            with self._lock:
                self.stats["gathers"] += 1
                if len(batch) >= self.max_batch:
                    self.stats["full_gathers"] += 1
            # dtype-homogeneous sub-batches: co-batching must never change
            # an eval's arithmetic (f32 evals upcast could select
            # differently than they would alone). int32 = the exact
            # integer parity spec; floats = throughput modes.
            for dtype in (np.int32, np.float64, np.float32):
                group = [r for r in batch if r.enc.dtype == dtype]
                if group:
                    self._run_batch_safe(group)

    def _run_batch_safe(self, batch: List[_Request]) -> None:
        try:
            self._run_batch(batch)
        except BaseException:  # noqa: BLE001 — confine the blast radius
            logger.exception(
                "batched dispatch failed; retrying %d evals individually",
                len(batch),
            )
            from .engine import TpuPlacementEngine

            engine = TpuPlacementEngine.shared()
            for req in batch:
                try:
                    req.result = engine.run_scan_single(req.enc)
                except BaseException as e:  # noqa: BLE001
                    req.error = e
                req.event.set()

    def _scan_fn(self):
        """The ONE batched-scan builder (engine._build_batched_scan),
        sharded over the configured mesh when present. Double-checked
        lock: the prewarm thread and the dispatcher both initialize
        lazily, and losing a duplicate build would orphan the loser's
        jit compile cache."""
        scan = self._scan
        if scan is None:
            with self._scan_lock:
                if self._scan is None:
                    shardings = None
                    if self.mesh is not None:
                        from ..parallel.sharding import batched_scan_shardings

                        shardings = batched_scan_shardings(self.mesh)
                    self._scan = _build_batched_scan(in_shardings=shardings)
                scan = self._scan
        return scan

    def _buckets(self) -> List[int]:
        mid = max(1, self.max_batch // 4)
        out = [1]
        if mid not in out:
            out.append(mid)
        if self.max_batch not in out:
            out.append(self.max_batch)
        if self.mesh is not None:
            ep = self.mesh.shape.get("evals", 1)
            out = sorted({((b + ep - 1) // ep) * ep for b in out})
        return out

    def _prewarm_siblings(self, one_padded, current_b_pad: int) -> None:
        """First sight of a padded shape: compile its OTHER batch buckets
        on a background thread by calling the scan with stacked inert
        copies. The persistent XLA cache makes repeats across restarts
        cheap, but even a cache HIT load is seconds — hide it off the
        dispatch path. Device time for the warming calls interleaves with
        real dispatches at the runtime's discretion; correctness is
        unaffected (results discarded)."""
        shape_key = tuple(
            (a.shape, str(a.dtype)) for part in one_padded for a in part
        )
        with self._lock:
            warmed = self._warmed.setdefault(shape_key, set())
            todo = [
                b for b in self._buckets()
                if b != current_b_pad and b not in warmed
            ]
            warmed.add(current_b_pad)
            if not todo:
                return
            warmed.update(todo)

        def warm() -> None:
            for b in todo:
                try:
                    stacked = tuple(
                        tuple(
                            np.stack([part[i]] * b)
                            for i in range(len(part))
                        )
                        for part in one_padded
                    )
                    scan = self._scan_fn()
                    np.asarray(scan(*stacked)[1][0])
                except BaseException:  # noqa: BLE001 — warming is best-effort
                    logger.debug("bucket prewarm failed", exc_info=True)

        t = threading.Thread(target=warm, name="batcher-prewarm", daemon=True)
        with self._lock:
            self._warm_threads.append(t)
        t.start()

    def wait_warm(self, timeout: Optional[float] = None) -> None:
        """Block until outstanding bucket-warming finishes (benches /
        boot sequences that want compiles out of their timed window).
        Tracking mutations stay under the lock so a warm thread spawned
        concurrently is never dropped unjoined."""
        while True:
            with self._lock:
                pending = [t for t in self._warm_threads if t.is_alive()]
                self._warm_threads = pending
            if not pending:
                return
            for t in pending:
                t.join(timeout=timeout)
            if timeout is not None:
                # one bounded pass only
                with self._lock:
                    self._warm_threads = [
                        t for t in self._warm_threads if t.is_alive()
                    ]
                return

    def dispatch_profile(self) -> Dict[str, object]:
        """Per-dispatch timing split + a roofline note for the batched
        placement scan: where does a dispatch's wall time go (host
        pad/stack vs device compute vs D2H transfer), and what D2H
        bandwidth does the transfer leg sustain? The note names the
        binding resource so four-rounds-flat throughput plateaus read as
        "compute-bound at X ms/dispatch" instead of a bare number."""
        with self._lock:
            s = dict(self.stats)
        n = s["dispatches"]
        if n == 0:
            return {"dispatches": 0, "note": "no dispatches recorded"}
        pad = s["pad_stack_ms_total"] / n
        comp = s["compute_ms_total"] / n
        xfer = s["transfer_ms_total"] / n
        gbps = 0.0
        if s["transfer_ms_total"] > 0:
            gbps = s["d2h_bytes_total"] / (s["transfer_ms_total"] / 1e3) / 1e9
        legs = {"pad/stack (host)": pad, "compute (device)": comp,
                "transfer (D2H)": xfer}
        bound = max(legs, key=legs.get)
        total = pad + comp + xfer
        note = (
            f"{bound}-bound: {legs[bound]:.2f}ms of {total:.2f}ms per "
            f"dispatch (pad/stack {pad:.2f}ms, compute {comp:.2f}ms, "
            f"transfer {xfer:.2f}ms at {gbps:.2f} GB/s D2H, "
            f"{s['evals'] / n:.1f} evals/dispatch)"
        )
        return {
            "dispatches": n,
            "evals": s["evals"],
            "pad_stack_ms_avg": round(pad, 3),
            "compute_ms_avg": round(comp, 3),
            "transfer_ms_avg": round(xfer, 3),
            "d2h_bytes_total": s["d2h_bytes_total"],
            "d2h_gbps": round(gbps, 3),
            "note": note,
        }

    def _run_batch(self, batch: List[_Request]) -> None:
        from ..utils import metrics
        from ..utils import phases as _phases

        t_start = metrics.now()
        encs = [r.enc for r in batch]
        # shared bucketed dims (pow2 to bound recompiles); G always gets a
        # padded slot so padded steps have a pre-failed TG to point at
        n_pad = max(_round_up(e.n_real) for e in encs)
        g_pad = _pow2ceil(max(e.g for e in encs) + 1)
        # S stays ZERO when no co-batched eval has spreads (the
        # compiled step skips the whole spread machinery); mixed
        # batches widen — same pattern as the affinity axis
        s_raw = max(e.s for e in encs)
        s_pad = _pow2ceil(s_raw) if s_raw else 0
        v_pad = _pow2ceil(max(max(e.v for e in encs), 2))
        # COARSE placement-count buckets (16/64/256/1024, pow2 beyond):
        # retried partial evals arrive at arbitrary small p, and a fresh
        # compile (even a persistent-cache load) per pow2 bucket costs
        # seconds — far more than the padded steps, which skip cheaply.
        # 257..1024 collapses into ONE bucket: a mid-run OCC retry of a
        # few hundred placements must ride the wave cohort's warm 1024
        # bucket, not stall the dispatcher on a fresh 512 compile.
        p_raw = max(e.p for e in encs)
        p_pad = (
            16 if p_raw <= 16 else 64 if p_raw <= 64
            else 256 if p_raw <= 256 else 1024 if p_raw <= 1024
            else _pow2ceil(p_raw)
        )
        d_pad = max(e.static[0].shape[1] for e in encs)
        # absent-feature axes stay ZERO when the whole batch lacks them
        # (the compiled step skips those ops); mixed batches widen
        k_pad = max(e.xs[1].shape[1] for e in encs)
        aff_raw = max(e.static[4].shape[0] for e in encs)
        aff_pad = g_pad if aff_raw else 0
        evd_raw = max(e.xs[3].shape[1] for e in encs)
        evd_pad = d_pad if evd_raw else 0
        fac_pad = max(e.xs[7].shape[1] for e in encs)
        dpd_pad = max(e.static[17].shape[0] for e in encs)
        dpv_pad = max(e.carry[8].shape[1] for e in encs)
        fnd_pad = max(e.xs[9].shape[1] for e in encs)
        # preemption candidate axis: zero when no co-batched eval preempts
        prec_raw = max(e.static[20].shape[1] for e in encs)
        prec_pad = _pow2ceil(prec_raw) if prec_raw else 0
        pregp_pad = (
            _pow2ceil(max(max(e.carry[11].shape[0] for e in encs), 1))
            if prec_pad else 0
        )
        dtype = encs[0].dtype  # dispatch loop groups by dtype

        with _phases.track("pad_stack"):
            static_b, carry_b, xs_b, b, b_pad = self._pad_and_stack(
                encs, n_pad, g_pad, s_pad, v_pad, p_pad, dtype, d_pad,
                k_pad, aff_pad, evd_pad, fac_pad, dpd_pad, dpv_pad, fnd_pad,
                prec_pad, pregp_pad,
            )

        scan = self._scan_fn()
        t_stack = metrics.now()
        metrics.measure_since("nomad.device_batcher.pad_stack", t_start)
        with _phases.track("device"):
            # compute vs transfer split: block_until_ready fences the
            # device work so np.asarray below times ONLY the D2H copy
            _carry, (chosen, scores, pulls, skipped, evict) = scan(
                static_b, carry_b, xs_b)
            try:
                import jax

                jax.block_until_ready((chosen, scores, pulls, skipped, evict))
            except Exception:  # noqa: BLE001 — non-jax outputs need no fence
                pass
            t_compute = metrics.now()
            chosen = np.asarray(chosen)
            scores = np.asarray(scores)
            pulls = np.asarray(pulls)
            skipped = np.asarray(skipped)
            evict = np.asarray(evict)
            t_transfer = metrics.now()
        metrics.measure_since("nomad.device_batcher.dispatch", t_stack)
        metrics.add_sample(
            "nomad.device_batcher.compute", (t_compute - t_stack) * 1000.0
        )
        metrics.add_sample(
            "nomad.device_batcher.transfer",
            (t_transfer - t_compute) * 1000.0,
        )
        d2h_bytes = (
            chosen.nbytes + scores.nbytes + pulls.nbytes + skipped.nbytes
            + evict.nbytes
        )

        with self._lock:
            self.stats["dispatches"] += 1
            self.stats["evals"] += b
            self.stats["padded_evals"] += b_pad - b
            self.stats["max_batch_seen"] = max(self.stats["max_batch_seen"], b)
            self.stats["pad_stack_ms_total"] += (t_stack - t_start) * 1000.0
            self.stats["compute_ms_total"] += (t_compute - t_stack) * 1000.0
            self.stats["transfer_ms_total"] += (t_transfer - t_compute) * 1000.0
            self.stats["d2h_bytes_total"] += d2h_bytes
            for req in batch:
                # t_start and t_enqueue share the monotonic clock
                wait_ms = (t_start - req.t_enqueue) * 1000.0
                if wait_ms > 0:
                    self.stats["gather_wait_ms_total"] += wait_ms
                    self.stats["gather_wait_ms_max"] = max(
                        self.stats["gather_wait_ms_max"], wait_ms
                    )

        for bi, req in enumerate(batch):
            p = req.enc.p
            req.result = (
                chosen[bi, :p], scores[bi, :p], pulls[bi, :p], skipped[bi, :p],
                evict[bi, :p],
            )
            req.event.set()

    def _pad_and_stack(self, encs, n_pad, g_pad, s_pad, v_pad, p_pad, dtype,
                       d_pad, k_pad, aff_pad, evd_pad, fac_pad, dpd_pad,
                       dpv_pad, fnd_pad, prec_pad=0, pregp_pad=0):
        padded = [
            pad_encoded(e, n_pad, g_pad, s_pad, v_pad, p_pad, dtype, d_pad,
                        k_pad, aff_pad, evd_pad, fac_pad, dpd_pad, dpv_pad,
                        fnd_pad, prec_pad, pregp_pad)
            for e in encs
        ]

        b = len(padded)
        # Three batch buckets — 1, max/4, max. Unrestricted pow2 buckets
        # each cost a tens-of-seconds XLA compile; but padding every small
        # batch to max wastes real device time (per-step cost grows with
        # the batch axis). Compiles are amortized by the persistent cache.
        mid = max(1, self.max_batch // 4)
        b_pad = 1 if b == 1 else (mid if b <= mid else self.max_batch)
        if self.mesh is not None:
            ep = self.mesh.shape.get("evals", 1)
            b_pad = ((b_pad + ep - 1) // ep) * ep
            nn = self.mesh.shape.get("nodes", 1)
            n_pad2 = ((n_pad + nn - 1) // nn) * nn
            if n_pad2 != n_pad:
                padded = [
                    pad_encoded(e, n_pad2, g_pad, s_pad, v_pad, p_pad, dtype,
                                d_pad, k_pad, aff_pad, evd_pad, fac_pad,
                                dpd_pad, dpv_pad, fnd_pad, prec_pad, pregp_pad)
                    for e in encs
                ]
                n_pad = n_pad2
        # Warm the SIBLING batch buckets of this shape in the background
        # (VERDICT r3 #3: precompile pinned buckets): the first dispatch
        # of a new shape pays its own compile/cache-load synchronously,
        # but the follow-up waves (smaller tails, single-eval retries)
        # must not stall multi-second on theirs. One zero-input call per
        # bucket populates the jit executable cache off the hot path.
        self._prewarm_siblings(padded[0], b_pad)

        while len(padded) < b_pad:
            padded.append(padded[0])  # inert copies; results discarded

        static_b = tuple(
            np.stack([p[0][i] for p in padded]) for i in range(len(padded[0][0]))
        )
        carry_b = tuple(
            np.stack([p[1][i] for p in padded]) for i in range(len(padded[0][1]))
        )
        xs_b = tuple(
            np.stack([p[2][i] for p in padded]) for i in range(len(padded[0][2]))
        )
        return static_b, carry_b, xs_b, b, b_pad
