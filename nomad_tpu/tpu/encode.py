"""Dense tensor encodings for the TPU placement engine.

This layer has no reference analog: it converts the host object graph
(nodes, task groups, plan state) into the arrays consumed by
``nomad_tpu.tpu.engine``. Feasibility is computed host-side *per computed
node class* (same memoization the reference uses in scheduler/context.go:191)
and gathered per node into mask vectors; string-world constraints therefore
never run on device.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs.structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    DeviceIdTuple,
    Job,
    Node,
    TaskGroup,
)

# Capacity dimensions tracked on device. Dims 4.. are DEVICE dims: each
# distinct device-ask id in the job claims one (totals = free matching
# instances per node at eval start). The per-eval dimensionality is
# 4 + the job's device-dim count, so deviceless jobs — the common case —
# pay nothing for the device model; the batcher pads D across a batch.
DIM_CPU, DIM_MEM, DIM_DISK, DIM_MBITS = 0, 1, 2, 3
DEVICE_DIMS = 2  # max distinct device asks per job on the engine path
NUM_DIMS = 4 + DEVICE_DIMS  # maximum


def job_num_dims(device_dims) -> int:
    return 4 + len(device_dims)

# Max penalty nodes encoded per placement (failed node + reschedule history).
MAX_PENALTY_NODES = 6


@dataclass
class NodeTable:
    """Per-node dense state for one evaluation."""

    nodes: List[Node]
    node_index: Dict[str, int]
    # [N, D] totals and reserved
    totals: np.ndarray
    reserved: np.ndarray
    # [N, D] used by proposed allocs at eval start
    used: np.ndarray
    # per-node count of proposed allocs of this job / per TG
    job_counts: np.ndarray  # [N]
    tg_counts: np.ndarray  # [G, N]


@dataclass
class TGSpec:
    """Per-task-group dense spec."""

    index: int
    name: str
    ask: np.ndarray  # [D]
    feasible: np.ndarray  # [N] bool (constraints AND port availability)
    affinity_score: np.ndarray  # [N] float32
    affinity_present: np.ndarray  # [N] bool
    desired_count: int
    distinct_hosts_job: bool
    distinct_hosts_tg: bool
    limit: int
    # spread: [S, N] value ids, [S, V] desired counts, [S] weights, [S, V] initial counts
    spread_vids: np.ndarray
    spread_desired: np.ndarray
    spread_weights: np.ndarray
    spread_counts0: np.ndarray
    spread_has_targets: np.ndarray  # [S] bool — targeted vs even-spread scoring
    sum_spread_weights: float
    widens: bool = False  # affinity/spread stanzas -> MaxInt32 limit
    # constraints only (drivers/constraints/volumes/devices), WITHOUT the
    # port-availability mask — the system path needs the split: a
    # port-occupied node is EXHAUSTED (failed + blocked eval, retried
    # when the port frees), not constraint-filtered out of the domain
    constraint_feasible: Optional[np.ndarray] = None  # [N] bool


class UnsupportedByEngine(Exception):
    """Raised when a job uses features the device engine doesn't accelerate;
    the caller falls back to the (semantically complete) host path."""


def _net_ask(tg: TaskGroup) -> Tuple[int, bool]:
    """Total mbits asked (group + tasks); flags reserved-port asks."""
    mbits = 0
    has_reserved_ports = False
    for net in tg.networks:
        mbits += net.mbits
        if net.reserved_ports:
            has_reserved_ports = True
    for task in tg.tasks:
        for net in task.resources.networks:
            mbits += net.mbits
            if net.reserved_ports:
                has_reserved_ports = True
    return mbits, has_reserved_ports


def _tg_reserved_ports(tg: TaskGroup) -> set:
    ports = set()
    for net in tg.networks:
        ports.update(p.value for p in net.reserved_ports)
    for task in tg.tasks:
        for net in task.resources.networks:
            ports.update(p.value for p in net.reserved_ports)
    return ports


def job_device_dims(job: Job) -> Dict[tuple, int]:
    """Map each distinct device-ask id in the job to a capacity dim
    (4..4+DEVICE_DIMS-1). Raises UnsupportedByEngine when the job's device
    shapes exceed what the conservative tensor model covers exactly."""
    dims: Dict[tuple, int] = {}
    for tg in job.task_groups:
        for task in tg.tasks:
            for ask in task.resources.devices:
                if ask.constraints or ask.affinities:
                    # constraints/affinities change feasibility/scoring per
                    # instance — host pipeline handles those
                    raise UnsupportedByEngine("device ask with constraints/affinities")
                if ask.count <= 0:
                    raise UnsupportedByEngine("device ask with zero count")
                key = ask.id()  # DeviceIdTuple (frozen, hashable)
                if key not in dims:
                    if len(dims) >= DEVICE_DIMS:
                        raise UnsupportedByEngine(
                            f"more than {DEVICE_DIMS} distinct device asks"
                        )
                    dims[key] = 4 + len(dims)
    return dims


def check_supported(job: Job, tg: TaskGroup) -> None:
    """Gate on features the engine doesn't model on device.

    Reserved ports, plain count-based device asks AND distinct_property
    ARE modeled (port-feasibility masks + same-TG-per-node exclusion;
    device capacity dims; value-count feasibility carry). Remaining
    fallbacks: cross-TG reserved-port overlap (two TGs competing for one
    port need the host's sequential port book-keeping) and device asks
    with constraints/affinities or more distinct ids than the spare dims.
    """
    job_device_dims(job)  # raises on unsupported device shapes
    mine = _tg_reserved_ports(tg)
    if mine:
        for other in job.task_groups:
            if other.name == tg.name:
                continue
            if mine & _tg_reserved_ports(other):
                raise UnsupportedByEngine("cross-TG reserved port overlap")


def _distinct_property_arrays(ctx, job: Job, nodes: List[Node]):
    """Dense encoding of distinct_property constraints (feasible.go:353
    DistinctPropertyIterator): per constraint, a value id per node, an
    allowed count, the set of task groups it applies to, and the
    existing+proposed-cleared base counts from the property set. The
    scan threads count mutation through its carry (same mechanism as
    spread counts) and filters nodes whose value is at the limit.

    Returns (dp_vids [D, N+1-bucketed], dp_limit [D], dp_applies [G, D],
    dp_counts0 [D, V]); D == 0 when the job has no distinct_property
    constraints (the step compiles the machinery away). Raises
    UnsupportedByEngine on an unparsable rtarget (the host path keeps
    its error messaging)."""
    from ..scheduler.propertyset import PropertySet, get_property

    entries = []  # (constraint, tg_name or "")
    for c in job.constraints:
        if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
            entries.append((c, ""))
    for tg in job.task_groups:
        for c in tg.constraints:
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
                entries.append((c, tg.name))

    n = len(nodes)
    g = len(job.task_groups)
    d_count = len(entries)
    if d_count == 0:
        return (
            np.zeros((0, n), np.int32), np.zeros(0, np.int32),
            np.zeros((g, 0), bool), np.zeros((0, 1), np.int32),
        )

    tg_index = {tg.name: gi for gi, tg in enumerate(job.task_groups)}
    vocabs: List[Dict[str, int]] = []
    node_vals: List[List[Optional[str]]] = []
    limits = np.ones(d_count, np.int32)
    applies = np.zeros((g, d_count), bool)
    for di, (c, tg_name) in enumerate(entries):
        if c.rtarget:
            try:
                limits[di] = int(c.rtarget)
            except ValueError:
                raise UnsupportedByEngine("distinct_property bad rtarget")
        if tg_name:
            applies[tg_index[tg_name], di] = True
        else:
            applies[:, di] = True
        vocab: Dict[str, int] = {}
        vals: List[Optional[str]] = []
        for node in nodes:
            val, ok = get_property(node, c.ltarget)
            if not ok:
                vals.append(None)
                continue
            vocab.setdefault(val, len(vocab))
            vals.append(val)
        vocabs.append(vocab)
        node_vals.append(vals)

    v = max((len(vb) for vb in vocabs), default=0) + 1  # +1 missing bucket
    vids = np.full((d_count, n), v - 1, np.int32)
    counts0 = np.zeros((d_count, v), np.int32)
    for di, (c, tg_name) in enumerate(entries):
        vocab = vocabs[di]
        for i in range(n):
            val = node_vals[di][i]
            if val is not None:
                vids[di, i] = vocab[val]
        pset = PropertySet(ctx, job)
        # set_*_constraint populates existing AND proposed/cleared from
        # the plan as-encoded (stops + in-place updates)
        if tg_name:
            pset.set_tg_constraint(c, tg_name)
        else:
            pset.set_job_constraint(c)
        for val, count in pset.get_combined_use_map().items():
            if val in vocab:
                counts0[di, vocab[val]] = count
    return vids, limits, applies, counts0


# ---------------------------------------------------------------------------
# Fleet-static cache: the per-node arrays that depend only on the node
# table (totals/reserved, index map, computed-class groups) are identical
# for every eval scheduled between two node writes. Keyed by the store's
# (store_id, node_epoch); valid only in deterministic mode, where the
# candidate order is the stable table order (non-deterministic evals
# shuffle per eval). Node objects are immutable-once-stored, so entries
# survive snapshots.
# ---------------------------------------------------------------------------

_FLEET_CACHE: Dict[tuple, dict] = {}
_FLEET_CACHE_MAX = 16

# Job fields that never influence placement encoding: identity, audit
# stamps and server-maintained status. EVERYTHING else (type, priority,
# datacenters, constraints/affinities/spreads, task groups, meta) is
# hashed — two jobs with equal signatures encode to identical arrays
# against the same fleet/usage state (reference precedent: the
# scheduler's per-class eligibility memoization keys on constraint
# content, context.go:191 / feasible.go:778; this extends the idea to
# the WHOLE per-eval encoding so a fleet of same-shaped jobs — the C1M
# workload — encodes once, not once per eval).
_SIG_EXCLUDE = frozenset((
    "id", "name", "parent_id", "status", "status_description", "stable",
    "version", "create_index", "modify_index", "job_modify_index",
    "submit_time", "payload",
))


def job_sched_signature(job: Job) -> bytes:
    """Content hash of the job's scheduling-relevant fields, cached on
    the job object (stored jobs are immutable and shared by snapshots,
    so the hash is computed once per job version)."""
    sig = job.__dict__.get("_sched_sig")
    if sig is None:
        import dataclasses
        import hashlib
        import pickle

        d = dataclasses.asdict(job)
        for k in _SIG_EXCLUDE:
            d.pop(k, None)
        sig = hashlib.blake2b(
            pickle.dumps(d, protocol=4), digest_size=16
        ).digest()
        job.__dict__["_sched_sig"] = sig
    return sig


def fleet_static(ctx, job: Job, nodes: List[Node]) -> Optional[dict]:
    """Cached {totals4, reserved4, node_index, class_groups, nodes} for
    this fleet, or None when caching can't be validated."""
    state = ctx.state
    store_id = getattr(state, "store_id", None)
    if store_id is None or not getattr(ctx, "deterministic", False):
        return None
    n = len(nodes)
    key = (
        store_id, getattr(state, "node_epoch", -1),
        tuple(job.datacenters), n,
    )
    ent = _FLEET_CACHE.get(key)
    if ent is not None:
        cn = ent["nodes"]
        # identity spot-checks guard against an aliased key ever handing
        # back arrays for a different node list
        if n == 0 or (
            cn[0] is nodes[0] and cn[-1] is nodes[-1]
            and cn[n // 2] is nodes[n // 2]
        ):
            return ent

    from ..structs.funcs import node_capacity_vecs

    totals4 = np.zeros((n, 4), dtype=np.float64)
    reserved4 = np.zeros((n, 4), dtype=np.float64)
    class_members: Dict[str, List[int]] = {}
    for i, node in enumerate(nodes):
        totals4[i], reserved4[i] = node_capacity_vecs(node)
        class_members.setdefault(node.computed_class, []).append(i)
    ent = {
        "nodes": list(nodes),
        "node_index": {node.id: i for i, node in enumerate(nodes)},
        "totals4": totals4,
        "reserved4": reserved4,
        "class_groups": [
            (idxs[0], np.asarray(idxs, np.int64))
            for idxs in class_members.values()
        ],
    }
    if len(_FLEET_CACHE) >= _FLEET_CACHE_MAX:
        _FLEET_CACHE.clear()
    _FLEET_CACHE[key] = ent
    return ent


from ..structs.funcs import alloc_usage_vec as _alloc_usage_vec


def _snapshot_usage(state) -> Dict[str, tuple]:
    """Per-node (cpu, mem, disk, mbits) of NON-terminal allocs at this
    snapshot. The state store maintains this incrementally on every alloc
    write (state_store._usage_delta) and snapshots share it by shallow
    copy; the fallback full scan covers stores restored from pre-mirror
    pickles."""
    nu = getattr(state, "_node_usage", None)
    if nu is not None:
        return nu
    usage: Dict[str, tuple] = {}
    for alloc in state.allocs():
        if alloc.terminal_status():
            continue
        u = _alloc_usage_vec(alloc)
        row = usage.get(alloc.node_id, (0.0, 0.0, 0.0, 0.0))
        usage[alloc.node_id] = (
            row[0] + u[0], row[1] + u[1], row[2] + u[2], row[3] + u[3]
        )
    return usage


def epoch_usage_arrays(ctx, fleet: dict, n_pad: int, int_mode: bool, fdtype):
    """Usage-epoch patch arrays for the whole-eval encode cache
    (engine.encode_eval): for a clean-plan, no-live-alloc, no-device-dim
    job, the ONLY encoded arrays that change between usage epochs are
    the base node usage (scan carry[0]) and its Q27 exponential chain
    (carry[7]) — and both are JOB-INDEPENDENT. One (used0, e_base0)
    pair per (fleet, usage-epoch) therefore refreshes EVERY cached
    eval, turning the epoch-roll re-encode (~30ms x O(nodes) per eval,
    the r5 1M run's dominant host phase) into an O(nodes) array swap
    computed once per commit wave. Same arithmetic as the inline
    encode-path derivation (int32 casts before the int64 free/capacity
    subtraction), so patched evals stay bit-identical to fresh ones."""
    import threading

    key = (getattr(ctx.state, "usage_epoch", -1), n_pad, int_mode)
    cached = fleet.get("epoch_usage")
    if cached is not None and cached[0] == key:
        return cached[1], cached[2]
    lock = fleet.setdefault("epoch_usage_lock", threading.Lock())
    with lock:
        cached = fleet.get("epoch_usage")
        if cached is not None and cached[0] == key:
            return cached[1], cached[2]
        node_index = fleet["node_index"]
        totals4 = fleet["totals4"]
        reserved4 = fleet["reserved4"]
        n_real = totals4.shape[0]
        used = np.zeros((n_pad, 4), np.float64)
        for node_id, row in _snapshot_usage(ctx.state).items():
            i = node_index.get(node_id)
            if i is not None:
                used[i, DIM_CPU] += row[0]
                used[i, DIM_MEM] += row[1]
                used[i, DIM_DISK] += row[2]
                used[i, DIM_MBITS] += row[3]
        used0 = used.astype(fdtype)
        if int_mode:
            from .intscore import e27_np, xq_np

            node_c2 = np.zeros((n_pad, 2), np.int64)
            # cast each operand to the eval dtype BEFORE subtracting —
            # matching the inline encode path, which assigns the float64
            # capacities into fdtype buffers first; subtracting in float64
            # and truncating after diverges on fractional capacities
            node_c2[:n_real] = (
                totals4[:, :2].astype(fdtype) - reserved4[:, :2].astype(fdtype)
            ).astype(np.int64)
            res2 = np.zeros((n_pad, 2), fdtype)
            res2[:n_real] = reserved4[:, :2]
            free0 = node_c2 - used0[:, :2] - res2
            e_base0 = e27_np(xq_np(free0, node_c2)).astype(np.int32)
        else:
            e_base0 = np.zeros((0, 2), np.int32)
        fleet["epoch_usage"] = (key, used0, e_base0)
        return used0, e_base0


def subset_encoded_rows(xs: tuple, missing_list: list, rows) -> tuple:
    """Row-subset of an eval's per-placement scan inputs.

    Every array in an EncodedEval's ``xs`` tuple carries the placement
    axis as its LEADING dim (batcher.pad_encoded relies on the same
    invariant to pad it), so a partial-OCC re-dispatch
    (pipeline/redispatch.py) can keep just the failed placements' rows:
    the scan replays only those steps against freshly patched usage
    (epoch_usage_arrays), skipping snapshot/encode entirely. Returns
    (xs_subset, missing_subset); node-axis arrays (static/carry) are
    untouched by construction.
    """
    sel = np.asarray(list(rows), np.int64)
    xs_sub = tuple(np.ascontiguousarray(a[sel]) for a in xs)
    ml_sub = [missing_list[int(k)] for k in sel]
    return xs_sub, ml_sub


def build_node_table(ctx, job: Job, nodes: List[Node],
                     fleet: Optional[dict] = None) -> NodeTable:
    """Encode nodes + proposed allocs into dense arrays.

    Usage comes from the snapshot-level cache plus per-plan adjustments
    (evictions/preemptions subtract, planned placements add — the same
    proposed-allocs algebra as context.go:120, applied as O(plan) deltas
    instead of O(nodes) queries). Job/TG counts come from the job's own
    alloc index. Device dims keep the per-node DeviceAccounter path
    (totals[4+k] = free instances of the job's k-th device-ask id; a node
    where the ask matches MORE than one device group falls back: a pooled
    count could admit a node whose single-group assignment fails).
    """
    from ..structs.devices import DeviceAccounter

    n = len(nodes)
    g = len(job.task_groups)
    tg_index = {tg.name: gi for gi, tg in enumerate(job.task_groups)}
    device_dims = job_device_dims(job)
    num_dims = job_num_dims(device_dims)

    used = np.zeros((n, num_dims), dtype=np.float64)
    job_counts = np.zeros(n, dtype=np.int32)
    tg_counts = np.zeros((g, n), dtype=np.int32)

    if fleet is not None and not device_dims:
        # static per-node arrays shared across evals (read-only: the
        # encode layer copies them into padded buffers, never mutates)
        node_index = fleet["node_index"]
        totals = fleet["totals4"]
        reserved = fleet["reserved4"]
    else:
        from ..structs.funcs import node_capacity_vecs

        node_index = {node.id: i for i, node in enumerate(nodes)}
        totals = np.zeros((n, num_dims), dtype=np.float64)
        reserved = np.zeros((n, num_dims), dtype=np.float64)
        for i, node in enumerate(nodes):
            totals[i, :4], reserved[i, :4] = node_capacity_vecs(node)

    # -- base usage from the snapshot cache ------------------------------
    base_usage = _snapshot_usage(ctx.state)
    for node_id, row in base_usage.items():
        i = node_index.get(node_id)
        if i is not None:
            used[i, DIM_CPU] += row[0]
            used[i, DIM_MEM] += row[1]
            used[i, DIM_DISK] += row[2]
            used[i, DIM_MBITS] += row[3]

    def _base_nonterminal(alloc_id: str):
        base = ctx.state.alloc_by_id(alloc_id)
        if base is None or base.terminal_status():
            return None
        return base

    def _adjust(alloc, sign: float, count_job: bool) -> None:
        i = node_index.get(alloc.node_id)
        if i is None:
            return
        u = _alloc_usage_vec(alloc)
        used[i, DIM_CPU] += sign * u[0]
        used[i, DIM_MEM] += sign * u[1]
        used[i, DIM_DISK] += sign * u[2]
        used[i, DIM_MBITS] += sign * u[3]
        if count_job and alloc.job_id == job.id:
            job_counts[i] += int(sign)
            gi = tg_index.get(alloc.task_group)
            if gi is not None:
                tg_counts[gi, i] += int(sign)

    # -- job/TG counts from the job's alloc index (job_id across ALL
    #    namespaces — matching the host anti-affinity, rank.go:509) ------
    for alloc in ctx.state.allocs_by_job_id(job.id):
        if alloc.terminal_status():
            continue
        i = node_index.get(alloc.node_id)
        if i is None:
            continue
        job_counts[i] += 1
        gi = tg_index.get(alloc.task_group)
        if gi is not None:
            tg_counts[gi, i] += 1

    # -- plan deltas (evictions / preemptions subtract; placements add,
    #    overriding in-place-updated ids like proposed_allocs' by_id) ----
    overridden = set()
    for entries in ctx.plan.node_allocation.values():
        for alloc in entries:
            overridden.add(alloc.id)
    for entries in ctx.plan.node_update.values():
        for alloc in entries:
            if alloc.id in overridden:
                continue  # planned version wins; handled below
            base = _base_nonterminal(alloc.id)
            if base is not None:
                _adjust(base, -1.0, count_job=True)
    for entries in ctx.plan.node_preemptions.values():
        for alloc in entries:
            if alloc.id in overridden:
                continue
            base = _base_nonterminal(alloc.id)
            if base is not None:
                _adjust(base, -1.0, count_job=True)
    for entries in ctx.plan.node_allocation.values():
        for alloc in entries:
            base = _base_nonterminal(alloc.id)
            if base is not None:
                # in-place update: planned version REPLACES the base one
                _adjust(base, -1.0, count_job=True)
            if not alloc.terminal_status():
                _adjust(alloc, +1.0, count_job=True)

    # -- device capacity dims (per-node accounter path; device jobs only) -
    if device_dims:
        for i, node in enumerate(nodes):
            if not node.node_resources.devices:
                continue
            proposed = ctx.proposed_allocs(node.id)
            accounter = DeviceAccounter(node)
            accounter.add_allocs(proposed)
            groups_claimed: Dict[DeviceIdTuple, int] = {}
            for ask_id, dim in device_dims.items():
                matching = [
                    (dev_id, inst) for dev_id, inst in accounter.devices.items()
                    if dev_id.matches(ask_id)
                ]
                if len(matching) > 1:
                    raise UnsupportedByEngine(
                        "device ask matches multiple groups on a node"
                    )
                if matching:
                    dev_id, inst = matching[0]
                    if dev_id in groups_claimed:
                        # two dims drawing from one pool would each see the
                        # full free count — double-counted capacity
                        raise UnsupportedByEngine(
                            "overlapping device asks share one device group"
                        )
                    groups_claimed[dev_id] = dim
                    totals[i, dim] = inst.free_count()

    return NodeTable(
        nodes=nodes,
        node_index=node_index,
        totals=totals,
        reserved=reserved,
        used=used,
        job_counts=job_counts,
        tg_counts=tg_counts,
    )


def _class_feasibility(ctx, job: Job, tg: TaskGroup, nodes: List[Node],
                       fleet: Optional[dict] = None) -> np.ndarray:
    """Per-node feasibility mask, memoized per computed class for non-escaped
    constraints (mirrors FeasibilityWrapper semantics, feasible.go:778).
    With a fleet cache, nodes are pre-grouped by computed class so the
    per-eval cost is O(classes) checker runs + one vectorized scatter,
    not an O(nodes) Python loop."""
    from ..scheduler.feasible import ConstraintChecker, DeviceChecker, DriverChecker, HostVolumeChecker
    from ..scheduler.util import task_group_constraints
    from ..structs.node_class import escaped_constraints

    job_checker = ConstraintChecker(ctx, job.constraints)
    tg_constr = task_group_constraints(tg)
    drivers = DriverChecker(ctx, tg_constr.drivers)
    constraints = ConstraintChecker(ctx, tg_constr.constraints)
    volumes = HostVolumeChecker(ctx)
    volumes.set_volumes(tg.volumes)
    devices = DeviceChecker(ctx)
    devices.set_task_group(tg)

    escaped = bool(
        escaped_constraints(list(job.constraints))
        or escaped_constraints(tg_constr.constraints)
    )

    def check(node) -> bool:
        return (
            job_checker.feasible(node)
            and drivers.feasible(node)
            and constraints.feasible(node)
            and volumes.feasible(node)
            and devices.feasible(node)
        )

    mask = np.zeros(len(nodes), dtype=bool)
    if not escaped and fleet is not None:
        for rep_idx, members in fleet["class_groups"]:
            if check(nodes[rep_idx]):
                mask[members] = True
        return mask

    class_cache: Dict[str, bool] = {}
    for i, node in enumerate(nodes):
        cls = node.computed_class
        if not escaped and cls in class_cache:
            mask[i] = class_cache[cls]
            continue
        ok = check(node)
        mask[i] = ok
        if not escaped:
            class_cache[cls] = ok
    return mask


def _affinity_arrays(ctx, job: Job, tg: TaskGroup, nodes: List[Node],
                     int_mode: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    """Per-node normalized affinity scores (rank.go:640 semantics).

    ``int_mode``: Q30 fixed-point integers, computed EXACTLY from the
    integer weights ((total << 30) // sum_abs — the intscore.py spec);
    otherwise float64 as the host pipeline computes them."""
    from ..scheduler.feasible import matches_affinity

    affinities = list(job.affinities) + list(tg.affinities)
    for task in tg.tasks:
        affinities.extend(task.affinities)

    n = len(nodes)
    score = np.zeros(n, dtype=np.int64 if int_mode else np.float64)
    present = np.zeros(n, dtype=bool)
    if not affinities:
        return score, present

    if int_mode:
        if any(float(a.weight) != int(a.weight) for a in affinities):
            raise UnsupportedByEngine("non-integer affinity weight")
        from .intscore import aff_fp_py

        sum_weight_i = sum(abs(int(a.weight)) for a in affinities)
        for i, node in enumerate(nodes):
            total = sum(
                int(aff.weight) for aff in affinities
                if matches_affinity(ctx, aff, node)
            )
            if total != 0 and sum_weight_i != 0:
                score[i] = aff_fp_py(total, sum_weight_i)
                present[i] = True
        return score, present

    sum_weight = sum(abs(float(a.weight)) for a in affinities)
    for i, node in enumerate(nodes):
        total = 0.0
        for aff in affinities:
            if matches_affinity(ctx, aff, node):
                total += float(aff.weight)
        if total != 0.0 and sum_weight != 0.0:
            score[i] = total / sum_weight
            present[i] = True
    return score, present


def _spread_arrays(ctx, job: Job, tg: TaskGroup, nodes: List[Node],
                   int_mode: bool = False):
    """Encode spreads: value-id per node per spread, desired counts, and the
    existing+proposed usage counts (from the propertyset at eval start).

    ``int_mode``: desired counts as EXACT integer hundredths
    (percent * total_count — the intscore.py spec; -1 = no target),
    integer weights/counts; otherwise float64."""
    from ..scheduler.propertyset import PropertySet, get_property

    spreads = list(tg.spreads) + list(job.spreads)
    s = len(spreads)
    n = len(nodes)
    ddt = np.int32 if int_mode else np.float64
    if s == 0:
        return (
            np.zeros((0, n), dtype=np.int32),
            np.zeros((0, 1), dtype=ddt),
            np.zeros((0,), dtype=ddt),
            np.zeros((0, 1), dtype=ddt),
            np.zeros((0,), dtype=bool),
            0 if int_mode else 0.0,
        )
    if int_mode:
        for spread in spreads:
            w = spread.weight
            # magnitude gates keep the fused targeted-spread numerator
            # (d - 100u) * w * 2**30 within int64 (intscore.py module doc)
            if float(w) != int(w) or not (0 <= int(w) <= 256):
                raise UnsupportedByEngine("spread weight outside int-spec range")
            for st in spread.spread_target:
                if float(st.percent) != int(st.percent) or not (0 <= int(st.percent) <= 100):
                    raise UnsupportedByEngine("spread percent outside int-spec range")
        if sum(int(sp.weight) for sp in spreads) <= 0:
            raise UnsupportedByEngine("zero spread weight sum")

    # Build vocab per spread: values seen on nodes + declared targets.
    vids = np.zeros((s, n), dtype=np.int32)
    vocab_sizes = []
    vocabs: List[Dict[str, int]] = []
    node_values: List[List[Optional[str]]] = []
    for si, spread in enumerate(spreads):
        vocab: Dict[str, int] = {}
        vals: List[Optional[str]] = []
        for st in spread.spread_target:
            vocab.setdefault(st.value, len(vocab))
        for node in nodes:
            val, ok = get_property(node, spread.attribute)
            if not ok:
                vals.append(None)
                continue
            vocab.setdefault(val, len(vocab))
            vals.append(val)
        vocabs.append(vocab)
        node_values.append(vals)
        vocab_sizes.append(max(len(vocab), 1))
    v = max(vocab_sizes)

    desired = np.full((s, v + 1), -1, dtype=ddt) if int_mode else \
        np.full((s, v + 1), -1.0, dtype=ddt)  # -1 = no target
    weights = np.zeros(s, dtype=ddt)
    counts0 = np.zeros((s, v + 1), dtype=ddt)
    has_targets = np.zeros(s, dtype=bool)

    total_count = tg.count
    sum_weights = 0 if int_mode else 0.0
    for si, spread in enumerate(spreads):
        weights[si] = spread.weight
        sum_weights += int(spread.weight) if int_mode else spread.weight
        vocab = vocabs[si]
        # node value ids (missing property -> v, the "invalid" bucket)
        for i in range(n):
            val = node_values[si][i]
            vids[si, i] = vocab[val] if val is not None else v
        if int_mode:
            # hundredths: d = percent * count (exact); the host's float
            # d = percent/100 * count is this value / 100
            sum_desired_h = 0
            for st in spread.spread_target:
                d_h = int(st.percent) * int(total_count)
                desired[si, vocab[st.value]] = d_h
                sum_desired_h += d_h
                has_targets[si] = True
            if 0 < sum_desired_h < 100 * int(total_count):
                remainder_h = 100 * int(total_count) - sum_desired_h
                for val, vid in vocab.items():
                    if desired[si, vid] < 0:
                        desired[si, vid] = remainder_h
        else:
            sum_desired = 0.0
            for st in spread.spread_target:
                d = (float(st.percent) / 100.0) * float(total_count)
                desired[si, vocab[st.value]] = d
                sum_desired += d
                has_targets[si] = True
            # implicit remainder bucket
            if 0 < sum_desired < float(total_count):
                remainder = float(total_count) - sum_desired
                for val, vid in vocab.items():
                    if desired[si, vid] < 0:
                        desired[si, vid] = remainder
        # existing + proposed usage counts via the propertyset
        pset = PropertySet(ctx, job)
        pset.set_target_attribute(spread.attribute, tg.name)
        for val, count in pset.get_combined_use_map().items():
            if val in vocab:
                counts0[si, vocab[val]] = count

    return vids, desired, weights, counts0, has_targets, sum_weights


def _alloc_used_ports(alloc) -> set:
    ports = set()
    ar = alloc.allocated_resources
    if ar is None:
        return ports
    for net in ar.shared.networks:
        ports.update(p.value for p in net.reserved_ports)
        ports.update(p.value for p in net.dynamic_ports)
    for tr in ar.tasks.values():
        for net in tr.networks:
            ports.update(p.value for p in net.reserved_ports)
            ports.update(p.value for p in net.dynamic_ports)
    return ports


def _port_feasibility(ctx, job: Job, tg: TaskGroup, nodes: List[Node],
                      port_cache: Optional[Dict[str, object]]) -> np.ndarray:
    """Per-node mask: are ALL of the TG's reserved ports free given the
    proposed allocs (the host's NetworkIndex reserved-port check, hoisted
    into a static mask)?

    Ports held by THIS job's SAME task group are excluded: same-TG
    occupancy is enforced dynamically by the scan (tg_counts + the
    port-self-exclusion dh flag), so a destructive update whose eviction
    frees the port still places on the same node — exactly the host's
    sequential behavior. Duplicate port values within the TG's own asks
    can never co-assign — all-False, as the host sequentially fails."""
    from ..structs.network import NetworkIndex

    mask = np.ones(len(nodes), dtype=bool)
    wanted: set = set()
    dupes = 0
    for net in tg.networks:
        wanted.update(p.value for p in net.reserved_ports)
        dupes += len(net.reserved_ports)
    for task in tg.tasks:
        for net in task.resources.networks:
            wanted.update(p.value for p in net.reserved_ports)
            dupes += len(net.reserved_ports)
    if not wanted:
        return mask
    if dupes != len(wanted):
        return np.zeros(len(nodes), dtype=bool)
    for i, node in enumerate(nodes):
        used = None if port_cache is None else port_cache.get(node.id)
        if used is None:
            # node-level reserved host ports
            ni = NetworkIndex(deterministic=ctx.deterministic)
            ni.set_node(node)
            base = set()
            for ports in ni.used_ports.values():
                base.update(ports)
            # per-(job, tg) alloc port usage
            by_owner: Dict[tuple, set] = {}
            for alloc in ctx.proposed_allocs(node.id):
                if alloc.terminal_status():
                    continue
                by_owner.setdefault(
                    (alloc.job_id, alloc.task_group), set()
                ).update(_alloc_used_ports(alloc))
            used = (base, by_owner)
            if port_cache is not None:
                port_cache[node.id] = used
        base, by_owner = used
        blocking = set(base)
        for owner, ports in by_owner.items():
            if owner != (job.id, tg.name):
                blocking.update(ports)
        if blocking.intersection(wanted):
            mask[i] = False
    return mask


def build_tg_spec(ctx, job: Job, tg: TaskGroup, nodes: List[Node], batch: bool,
                  port_cache: Optional[Dict[str, object]] = None,
                  fleet: Optional[dict] = None) -> TGSpec:
    import math

    check_supported(job, tg)
    device_dims = job_device_dims(job)
    # deterministic mode scores on the exact integer spec (intscore.py):
    # int32 capacity arrays, Q30 affinity ints, hundredths spread targets
    int_mode = bool(getattr(ctx, "deterministic", False))

    ask = np.zeros(job_num_dims(device_dims),
                   dtype=np.int32 if int_mode else np.float64)
    for task in tg.tasks:
        ask[DIM_CPU] += task.resources.cpu
        ask[DIM_MEM] += task.resources.memory_mb
        for dev in task.resources.devices:
            ask[device_dims[dev.id()]] += dev.count
    ask[DIM_DISK] = tg.ephemeral_disk.size_mb
    ask[DIM_MBITS], _ = _net_ask(tg)

    constraint_feasible = _class_feasibility(ctx, job, tg, nodes, fleet=fleet)
    feasible = constraint_feasible & _port_feasibility(ctx, job, tg, nodes, port_cache)
    affinity_score, affinity_present = _affinity_arrays(
        ctx, job, tg, nodes, int_mode=int_mode
    )
    vids, desired, weights, counts0, has_targets, sum_weights = _spread_arrays(
        ctx, job, tg, nodes, int_mode=int_mode
    )

    # Base candidate limit (reference stack.go:74-86). The MaxInt32 widening
    # when affinity/spread stanzas exist is sticky across selects within one
    # set_nodes scope — resolved per placement by the engine driver.
    n = len(nodes)
    limit = 2
    if not batch and n > 0:
        limit = max(limit, int(math.ceil(math.log2(n))) if n > 1 else 2)

    has_affinity_stanzas = bool(
        list(job.affinities) or list(tg.affinities)
        or any(task.affinities for task in tg.tasks)
    )
    widens = has_affinity_stanzas or bool(list(tg.spreads) + list(job.spreads))

    gi = next(i for i, g in enumerate(job.task_groups) if g.name == tg.name)

    dh_job = any(c.operand == CONSTRAINT_DISTINCT_HOSTS for c in job.constraints)
    # reserved ports make the TG self-exclusive per node: a second instance
    # would collide on the same port, exactly the dh_tg blocking shape
    dh_tg = (
        any(c.operand == CONSTRAINT_DISTINCT_HOSTS for c in tg.constraints)
        or bool(_tg_reserved_ports(tg))
    )

    return TGSpec(
        index=gi,
        name=tg.name,
        ask=ask,
        feasible=feasible,
        constraint_feasible=constraint_feasible,
        affinity_score=affinity_score,
        affinity_present=affinity_present,
        desired_count=tg.count,
        distinct_hosts_job=dh_job,
        distinct_hosts_tg=dh_tg,
        limit=limit,
        widens=widens,
        spread_vids=vids,
        spread_desired=desired,
        spread_weights=weights,
        spread_counts0=counts0,
        spread_has_targets=has_targets,
        sum_spread_weights=sum_weights,
    )


# ---------------------------------------------------------------------------
# Preemption candidate tables (device-side eviction, tpu/preempt.py)
# ---------------------------------------------------------------------------


@dataclass
class PreemptTables:
    """Per-node current-allocation tables for the device preemption
    kernel: one slot per ELIGIBLE candidate (has a job, priority at least
    PRIORITY_DELTA below the placing job's, not the placing job's own).
    Ineligible non-own-job allocs only contribute to ``remaining3`` (the
    reference subtracts every candidate from node remaining, eligible or
    not; own-job allocs are invisible to the met-check)."""

    c: int            # candidate slots per node (>= 1)
    gp: int           # distinct (job_id, ns, task_group) count groups
    res4: np.ndarray  # [N, C, 4] int32 (cpu, mem, disk, mbits)
    prio: np.ndarray  # [N, C] int32
    elig: np.ndarray  # [N, C] bool
    mp: np.ndarray    # [N, C] int32 max_parallel
    gid: np.ndarray   # [N, C] int32 count-group id
    remaining3: np.ndarray  # [N, 3] int64
    counts0: np.ndarray     # [GP] int32 preemption counts at eval start
    allocs: List[List[object]]  # [N][<=C] candidate Allocation objects


def build_preempt_tables(ctx, job, nodes: List[Node]):
    """Build PreemptTables for one eval, or (None, reason) when a spec
    gate fails (the engine must then fall back to the host stack for the
    WHOLE eval — encoding without preemption would diverge from a
    preempting host oracle)."""
    from ..structs.funcs import alloc_usage_vec, node_capacity_vecs
    from .preempt import C_MAX, GP_MAX, PRIORITY_DELTA, RES_CAP as _RES_CAP

    job_key = (job.namespace, job.id)
    job_priority = job.priority

    n = len(nodes)
    per_node: List[List[object]] = [[] for _ in range(n)]
    remaining3 = np.empty((n, 3), np.int64)
    gid_map: Dict[Tuple[str, str, str], int] = {}
    c_max_seen = 0

    for i, node in enumerate(nodes):
        totals, reserved = node_capacity_vecs(node)
        rem = [
            int(totals[0]) - int(reserved[0]),
            int(totals[1]) - int(reserved[1]),
            int(totals[2]) - int(reserved[2]),
        ]
        cands = per_node[i]
        for alloc in ctx.proposed_allocs(node.id):
            if (alloc.namespace, alloc.job_id) == job_key:
                continue
            u = alloc_usage_vec(alloc)
            if max(u[0], u[1], u[2], u[3]) > _RES_CAP:
                return None, "preempt: candidate resources exceed 2**28"
            rem[0] -= int(u[0])
            rem[1] -= int(u[1])
            rem[2] -= int(u[2])
            if alloc.job is None or job_priority - alloc.job.priority < PRIORITY_DELTA:
                continue
            cands.append(alloc)
            key = (alloc.job_id, alloc.namespace, alloc.task_group)
            if key not in gid_map:
                gid_map[key] = len(gid_map)
        remaining3[i] = rem
        if len(cands) > C_MAX:
            return None, "preempt: too many candidates on one node"
        if len(cands) > c_max_seen:
            c_max_seen = len(cands)

    gp = len(gid_map)
    if gp > GP_MAX:
        return None, "preempt: too many count groups"
    c = max(c_max_seen, 1)

    res4 = np.zeros((n, c, 4), np.int32)
    prio = np.zeros((n, c), np.int32)
    elig = np.zeros((n, c), bool)
    mp = np.zeros((n, c), np.int32)
    gid = np.zeros((n, c), np.int32)
    for i in range(n):
        for j, alloc in enumerate(per_node[i]):
            u = alloc_usage_vec(alloc)
            res4[i, j] = (int(u[0]), int(u[1]), int(u[2]), int(u[3]))
            prio[i, j] = alloc.job.priority
            elig[i, j] = True
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg is not None and tg.migrate is not None:
                mp[i, j] = tg.migrate.max_parallel
            gid[i, j] = gid_map[(alloc.job_id, alloc.namespace, alloc.task_group)]

    # Preemption counts already in the plan (the reference's
    # set_preemptions at each node visit).
    counts0 = np.zeros(max(gp, 1), np.int32)
    for allocs in ctx.plan.node_preemptions.values():
        for alloc in allocs:
            g = gid_map.get((alloc.job_id, alloc.namespace, alloc.task_group))
            if g is not None:
                counts0[g] += 1

    return (
        PreemptTables(
            c=c, gp=max(gp, 1), res4=res4, prio=prio, elig=elig, mp=mp,
            gid=gid, remaining3=remaining3, counts0=counts0, allocs=per_node,
        ),
        None,
    )
